"""Serve LLM layer: LLMConfig, LLMServer, build_openai_app.

Reference: python/ray/serve/llm/__init__.py:33,75,178 (LLMConfig,
LLMServer, build_openai_app over a vLLM engine). Here the engine is the
in-tree TPU-native continuous-batching engine (ray_tpu.llm.engine); the
OpenAI-compatible surface exposes /v1/completions and
/v1/chat/completions through the serve HTTP proxy.

A replica owns one engine plus a background stepper thread; concurrent
requests land in the engine's waiting queue and share decode batches —
the continuous-batching path the reference gets from vLLM.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu import serve
from ray_tpu.llm.engine import (
    ContinuousBatchingEngine, EngineConfig, EngineSaturatedError,
    GenerationRequest)
from ray_tpu.llm.guided import (
    json_object_constraint, json_schema_constraint, parse_tool_call,
    tool_call_constraint)
from ray_tpu.llm.tokenizer import get_tokenizer


# cap on per-replica compiled guided-decoding constraints (LRU)
_MAX_CONSTRAINTS = 32


@dataclass
class LLMConfig:
    """Reference analog: serve/llm LLMConfig (model_loading_config +
    engine_kwargs + deployment_config)."""

    model_id: str = "llama-tiny"
    engine: EngineConfig = field(default_factory=EngineConfig)
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # route by prompt-prefix affinity (KV/prefix-cache locality;
    # reference: llm/_internal/serve/routing_policies/prefix_aware/)
    prefix_routing: bool = False
    # generation defaults
    max_tokens: int = 64
    temperature: float = 0.0


def stream_text_deltas(tokenizer, request):
    """Incremental detokenization over a request's stream queue: decode
    the full output each step and emit the text delta, holding back
    while the tail is an incomplete multi-byte/multi-piece character
    (U+FFFD) so streamed text matches the non-streamed decode exactly
    (reference: vLLM output streams behind serve token streaming).
    Shared by the co-located and disaggregated streaming paths."""
    out_ids: List[int] = []
    emitted = ""
    while True:
        token = request.stream_queue.get()
        if token is None:
            break
        if token in request.stop_ids:
            continue
        out_ids.append(token)
        text = tokenizer.decode(out_ids)
        if text.endswith("�"):
            continue
        delta = text[len(emitted):]
        if delta:
            emitted = text
            yield delta
    if request.error is not None:
        raise RuntimeError(request.error)
    final = tokenizer.decode(out_ids)
    if len(final) > len(emitted):
        yield final[len(emitted):]


def stream_token_deltas(tokenizer, request):
    """Like :func:`stream_text_deltas`, but yields exactly ONE delta per
    non-stop generated token — the contract the OpenAI SSE surface
    advertises ("per-token chunks"). When a token lands mid-way through
    a multi-byte character the decoded tail is U+FFFD; the text-delta
    variant silently merges it into the next token's delta, shifting
    chunk counts. Here the incomplete token yields ``""`` and the text
    catches up on a later token, via one-token lookahead so the final
    token's delta can absorb any held-back tail."""
    out_ids: List[int] = []
    emitted = ""
    pending = False
    while True:
        token = request.stream_queue.get()
        if token is None:
            break
        if token in request.stop_ids:
            continue
        if pending:
            text = tokenizer.decode(out_ids)
            if text.endswith("�"):
                yield ""
            else:
                delta = text[len(emitted):]
                emitted = text
                yield delta
        out_ids.append(token)
        pending = True
    if request.error is not None:
        raise RuntimeError(request.error)
    if pending:
        final = tokenizer.decode(out_ids)
        yield final[len(emitted):]


class LLMServer:
    """Deployment class hosting one engine per replica."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None):
        params = None
        if params_blob is not None:
            from ray_tpu.core import serialization
            params = serialization.loads(params_blob)
        self.config = config
        self.engine = ContinuousBatchingEngine(config.engine, params)
        self.tokenizer = get_tokenizer(config.engine.tokenizer)
        if self.tokenizer.vocab_size > config.engine.model.vocab_size:
            raise ValueError(
                f"tokenizer vocab ({self.tokenizer.vocab_size}) exceeds "
                f"model vocab ({config.engine.model.vocab_size}); token "
                "embedding lookups would silently clamp")
        # guided decoding: compiled constraints memoized per schema /
        # tool set (mask caches inside them warm across requests)
        self._constraint_cache: Dict[Any, Any] = {}
        self._token_strs: Optional[List[Optional[str]]] = None
        self._wake = threading.Event()
        self._stopped = False
        self._stepper = threading.Thread(target=self._step_loop,
                                         daemon=True)
        self._stepper.start()

    def stop(self) -> None:
        """Halt the stepper thread and fail in-flight requests — called
        when a multiplex LRU evicts this model from a replica."""
        self._stopped = True
        self._wake.set()
        self.engine.fail_all("model evicted from replica")

    def _step_loop(self) -> None:
        while not self._stopped:
            try:
                if self.engine.has_work():
                    self.engine.step()
                else:
                    self._wake.wait(0.002)
                    self._wake.clear()
            except Exception as e:  # noqa: BLE001 — keep serving
                # fail in-flight requests instead of hanging them; the
                # engine stays up for subsequent requests
                self.engine.fail_all(f"engine step failed: {e!r}")

    def _validate_sampling(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Validate/clamp client sampling params before they reach the
        shared stepper thread — a bad value raising inside step() would
        fail every in-flight request on the replica, not just this one.
        """
        import math

        out: Dict[str, Any] = {}
        max_tokens = body.get("max_tokens")
        if max_tokens is None:
            # newer OpenAI name (chat): max_completion_tokens
            max_tokens = body.get("max_completion_tokens")
        if max_tokens is not None:
            if (isinstance(max_tokens, bool)
                    or not isinstance(max_tokens, int) or max_tokens < 1):
                raise ValueError("max_tokens must be a positive integer")
            out["max_tokens"] = min(max_tokens,
                                    self.config.engine.model.max_seq_len)
        temperature = body.get("temperature")
        if temperature is not None:
            if (isinstance(temperature, bool)
                    or not isinstance(temperature, (int, float))
                    or math.isnan(float(temperature))
                    or not 0.0 <= float(temperature) <= 100.0):
                raise ValueError("temperature must be a number in [0, 100]")
            # sub-epsilon temperatures overflow the float32 logit divide
            # to inf/NaN inside the stepper; they mean "greedy" anyway
            out["temperature"] = (0.0 if float(temperature) < 1e-3
                                  else float(temperature))
        top_k = body.get("top_k", 0)
        if isinstance(top_k, bool) or not isinstance(top_k, int) or top_k < 0:
            raise ValueError("top_k must be a non-negative integer")
        # clamp to vocab: the on-device sampler clips to its static
        # top-k width anyway, but a sane bound keeps intent clear
        out["top_k"] = min(top_k, self.config.engine.model.vocab_size)
        out["adapter"] = self._resolve_adapter(body.get("model"))
        for pen in ("presence_penalty", "frequency_penalty"):
            val = body.get(pen)
            if val is None:
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)) \
                    or not math.isfinite(float(val)) \
                    or not -2.0 <= float(val) <= 2.0:
                raise ValueError(f"{pen} must be a number in [-2, 2]")
            out[pen] = float(val)
        so = body.get("stream_options")
        if so is not None:
            if not body.get("stream"):
                raise ValueError("stream_options requires stream=true")
            if not isinstance(so, dict) or not isinstance(
                    so.get("include_usage", False), bool):
                raise ValueError(
                    'stream_options must be {"include_usage": bool}')
            out["include_usage"] = bool(so.get("include_usage"))
        lp = body.get("logprobs")
        top_lp = body.get("top_logprobs")
        if lp is not None or top_lp is not None:
            if isinstance(lp, bool):
                # chat shape: logprobs: true + top_logprobs: int
                if top_lp is None:
                    top_lp = 0
                if isinstance(top_lp, bool) or \
                        not isinstance(top_lp, int) or \
                        not 0 <= top_lp <= 20:
                    raise ValueError(
                        "top_logprobs must be an integer in [0, 20]")
                if not lp and body.get("top_logprobs") is not None:
                    raise ValueError(
                        "top_logprobs requires logprobs=true")
                if lp:
                    out["logprobs"] = top_lp
            elif lp is not None:
                # completions shape: logprobs: int (0 = chosen only)
                if not isinstance(lp, int) or not 0 <= lp <= 5:
                    raise ValueError(
                        "logprobs must be an integer in [0, 5]")
                out["logprobs"] = lp
            else:
                raise ValueError("top_logprobs requires logprobs")
        lb = body.get("logit_bias")
        if lb is not None:
            if not isinstance(lb, dict):
                raise ValueError("logit_bias must be an object of "
                                 "{token_id: bias}")
            vocab = self.config.engine.model.vocab_size
            clean = {}
            for tid, val in lb.items():
                try:
                    t = int(tid)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"logit_bias key {tid!r} is not a token id")
                if not 0 <= t < vocab:
                    raise ValueError(
                        f"logit_bias token id {t} outside vocab "
                        f"[0, {vocab})")
                if isinstance(val, bool) or \
                        not isinstance(val, (int, float)) or \
                        not math.isfinite(float(val)):
                    raise ValueError(
                        f"logit_bias value for {t} must be a finite "
                        "number")
                clean[t] = float(val)
            out["logit_bias"] = clean
        n = body.get("n")
        if n is not None:
            if isinstance(n, bool) or not isinstance(n, int) or \
                    not 1 <= n <= 8:
                raise ValueError("n must be an integer in [1, 8]")
            out["n"] = n
        stop = body.get("stop")
        if stop is not None:
            if isinstance(stop, str):
                stop = [stop]
            if (not isinstance(stop, list) or not stop or len(stop) > 4
                    or not all(isinstance(s, str) and s for s in stop)):
                raise ValueError("stop must be a non-empty string or "
                                 "a list of 1-4 non-empty strings")
            out["stop"] = list(stop)
        return out

    # -- guided decoding: tools / tool_choice / response_format --------
    # (reference surface: openai_api_models.py:14-38 — vLLM's request
    # models; enforcement here is the in-tree TPU-native grammar-mask
    # path in ray_tpu.llm.guided)

    def _vocab_strings(self) -> List[Optional[str]]:
        if self._token_strs is None:
            self._token_strs = self.tokenizer.token_strings()
        return self._token_strs

    def _cached_constraint(self, key, build):
        # Bounded LRU: one compiled NFA + its per-state mask caches
        # per distinct schema/tool-set — unbounded retention would let
        # clients rotating unique schemas grow replica memory without
        # limit. Module constant (not class attribute): this method is
        # borrowed by PrefillServer/DisaggRouter in llm/disagg.py.
        cache = self._constraint_cache
        c = cache.get(key)
        if c is None:
            c = build()
            cache[key] = c
            while len(cache) > _MAX_CONSTRAINTS:
                cache.pop(next(iter(cache)))
        else:
            # re-insert = recency bump (plain dict preserves order)
            cache.pop(key)
            cache[key] = c
        return c

    def _resolve_guided(self, body: Dict[str, Any],
                        allow_tools: bool = True) -> Dict[str, Any]:
        """Validate tools/tool_choice/response_format and build the
        grammar constraint. Returns {"constraint", "kind",
        "tool_mode" (None|"auto"|"forced"), "tool_names"}."""
        tools = body.get("tools")
        tool_choice = body.get("tool_choice")
        rf = body.get("response_format")
        out: Dict[str, Any] = {"constraint": None, "kind": None,
                               "tool_mode": None, "tool_names": []}

        rf_type = None
        if rf is not None:
            if not isinstance(rf, dict) or rf.get("type") not in (
                    "text", "json_object", "json_schema"):
                raise ValueError(
                    'response_format.type must be "text", "json_object"'
                    ' or "json_schema"')
            rf_type = None if rf["type"] == "text" else rf["type"]

        if tools is not None and not allow_tools:
            raise ValueError(
                "tools are only supported on /v1/chat/completions")
        names: List[str] = []
        if tools is not None:
            if not isinstance(tools, list) or not tools:
                raise ValueError("tools must be a non-empty list")
            for t in tools:
                fn = t.get("function") if isinstance(t, dict) else None
                if (not isinstance(t, dict)
                        or t.get("type") != "function"
                        or not isinstance(fn, dict)
                        or not isinstance(fn.get("name"), str)
                        or not fn["name"]):
                    raise ValueError(
                        'each tool must be {"type": "function", '
                        '"function": {"name": ...}}')
                if fn.get("parameters") is not None and \
                        not isinstance(fn["parameters"], dict):
                    raise ValueError(
                        "tool function.parameters must be an object")
                names.append(fn["name"])
            if len(set(names)) != len(names):
                raise ValueError("duplicate tool function names")
        out["tool_names"] = names

        choice = tool_choice
        if choice is None:
            choice = "auto" if tools else "none"
        forced_name = None
        if isinstance(choice, dict):
            fn = choice.get("function")
            if choice.get("type") != "function" or \
                    not isinstance(fn, dict) or \
                    not isinstance(fn.get("name"), str):
                raise ValueError(
                    'tool_choice object must be {"type": "function", '
                    '"function": {"name": ...}}')
            forced_name = fn["name"]
            if forced_name not in names:
                raise ValueError(
                    f"tool_choice names unknown function {forced_name!r}")
        elif choice not in ("none", "auto", "required"):
            raise ValueError(
                'tool_choice must be "none", "auto", "required" or a '
                "named function object")
        if tool_choice is not None and tool_choice != "none" \
                and not tools:
            raise ValueError("tool_choice requires tools")

        eos = self.tokenizer.eos_id
        vocab = self._vocab_strings
        constrained_tools = tools is not None and (
            choice == "required" or forced_name is not None)
        if constrained_tools:
            if rf_type is not None:
                raise ValueError(
                    "response_format cannot be combined with a forced "
                    "tool_choice")
            key = ("tools", json.dumps(tools, sort_keys=True),
                   forced_name)
            out["constraint"] = self._cached_constraint(
                key, lambda: tool_call_constraint(
                    tools, vocab(), eos, forced_name=forced_name))
            out["kind"] = "tools"
            out["tool_mode"] = "forced"
            return out
        if tools is not None and choice == "auto" and rf_type is None:
            out["tool_mode"] = "auto"
        if rf_type == "json_object":
            out["constraint"] = self._cached_constraint(
                ("json_object",),
                lambda: json_object_constraint(vocab(), eos))
            out["kind"] = "json_object"
        elif rf_type == "json_schema":
            js = rf.get("json_schema")
            if not isinstance(js, dict) or \
                    not isinstance(js.get("schema"), dict):
                raise ValueError(
                    "response_format.json_schema.schema is required")
            key = ("schema", json.dumps(js["schema"], sort_keys=True))
            out["constraint"] = self._cached_constraint(
                key, lambda: json_schema_constraint(
                    js["schema"], vocab(), eos))
            out["kind"] = "json_schema"
        return out

    def _chat_prompt(self, body: Dict[str, Any],
                     messages: List[Dict[str, Any]]) -> str:
        """Render the chat template: tool definitions (when given) as
        a leading segment, then one segment per message; assistant
        tool_calls and tool results render as JSON text."""
        parts = []
        tools = body.get("tools")
        if tools:
            parts.append("<|tools|>" + json.dumps(
                tools, separators=(",", ":"), sort_keys=True))
        for m in messages:
            role = m.get("role", "user")
            if m.get("tool_calls") is not None:
                content = json.dumps(m["tool_calls"],
                                     separators=(",", ":"),
                                     sort_keys=True)
            else:
                content = self._flatten_content(m.get("content") or "")
            parts.append(f"<|{role}|>{content}")
        return "".join(parts) + "<|assistant|>"

    def _chat_message(self, guided_info: Optional[Dict[str, Any]],
                      result: Dict[str, Any]):
        """(message, finish_reason) for one chat choice: tool-call
        output parses into OpenAI tool_calls with finish_reason
        "tool_calls"; everything else is assistant content."""
        text = result["text"]
        finish = result["finish_reason"]
        if guided_info and guided_info["tool_mode"] is not None:
            parsed = parse_tool_call(text, guided_info["tool_names"])
            if parsed is not None:
                call = {
                    "id": f"call_{uuid.uuid4().hex[:24]}",
                    "type": "function",
                    "function": {
                        "name": parsed["name"],
                        "arguments": json.dumps(
                            parsed["arguments"],
                            separators=(",", ":"))}}
                return ({"role": "assistant", "content": None,
                         "tool_calls": [call]}, "tool_calls")
        return {"role": "assistant", "content": text}, finish

    # head of a grammar-shaped tool call; used to classify streams
    _TOOL_HEAD = re.compile(r'^\{"name":("(?:[^"\\]|\\.)*"),"arguments":')

    @staticmethod
    def _tool_head_prefix_ok(buf: str) -> bool:
        """Could ``buf`` still grow into a tool-call head? Decides how
        long an auto-mode stream is buffered before being classified
        as plain content."""
        probe = '{"name":"'
        if len(buf) <= len(probe):
            return probe.startswith(buf)
        if not buf.startswith(probe):
            return False
        i = len(probe)
        while i < len(buf):
            ch = buf[i]
            if ch == "\\":
                i += 2
                continue
            if ch == '"':
                break
            i += 1
        else:
            return True  # still inside the name string
        rest = buf[i + 1:]  # after the name's closing quote
        tail = ',"arguments":'
        return tail.startswith(rest) or rest.startswith(tail)

    def _stream_tool_events(self, deltas, tool_names: List[str]):
        """Classify a token stream into ("content", text) /
        ("tool_head", name) / ("tool_args", text) events. Tool-call
        argument text streams incrementally with a 1-char holdback so
        the grammar's closing wrapper brace is never emitted."""
        buf = ""
        decided = None
        sent = 0
        for delta in deltas:
            buf += delta
            if decided is None:
                m = self._TOOL_HEAD.match(buf)
                if m:
                    name = json.loads(m.group(1))
                    if not tool_names or name in tool_names:
                        decided = "tool"
                        sent = m.end()
                        yield ("tool_head", name)
                    else:
                        decided = "content"
                        yield ("content", buf)
                        continue
                elif self._tool_head_prefix_ok(buf):
                    continue
                else:
                    decided = "content"
                    yield ("content", buf)
                    continue
            if decided == "content":
                yield ("content", delta)
            else:
                avail = len(buf) - 1  # hold back the wrapper brace
                if avail > sent:
                    yield ("tool_args", buf[sent:avail])
                    sent = avail
        if decided == "tool":
            end = len(buf) - 1 if buf.endswith("}") else len(buf)
            if end > sent:
                yield ("tool_args", buf[sent:end])
        elif decided is None and buf:
            yield ("content", buf)

    def _make_request(self, prompt: str, *, max_tokens, temperature,
                      top_k, adapter, logit_bias, guided=None,
                      presence_penalty=0.0, frequency_penalty=0.0,
                      logprobs=None, stream_queue=None):
        """ONE construction + admission path for all generate
        variants (non-stream, stop-string, stream) so a new sampling
        field cannot desync them."""
        ids = self.tokenizer.encode(prompt)
        request = GenerationRequest(
            prompt_ids=ids,
            max_tokens=max_tokens or self.config.max_tokens,
            temperature=(self.config.temperature if temperature is None
                         else temperature),
            top_k=top_k,
            adapter=adapter,
            logit_bias=logit_bias,
            guided=guided,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
            logprobs=logprobs,
            stop_ids=(self.tokenizer.eos_id,)
            if self.tokenizer.eos_id is not None else (),
            stream_queue=stream_queue)
        try:
            self.engine.add_request(request)
        except EngineSaturatedError as exc:
            # reject-before-enqueue: surface typed backpressure so the
            # replica returns a Shed sentinel and the proxy answers
            # 503 + Retry-After instead of queueing behind the batch
            from ray_tpu.serve.admission import BackpressureError
            retry_after = min(30.0, 0.5 + 0.1 * exc.waiting)
            raise BackpressureError(self.config.model_id, retry_after,
                                    "engine_saturated") from exc
        self._wake.set()
        if self._stopped:
            # raced an LRU eviction: stop() set _stopped before its
            # fail_all; covering a request admitted after that sweep
            self.engine.fail_all("model evicted from replica")
        return ids, request

    def _generate_n(self, prompt: str,
                    sampling: Dict[str, Any]) -> List[Dict[str, Any]]:
        """n independent samples of one prompt (OpenAI `n`). Plain
        sampled requests are admitted together and co-batch in the
        engine, waited by ONE loop — no per-choice polling threads;
        stop-string requests need a stream consumer each, so n>1 with
        stop keeps a small thread pool."""
        n = sampling.get("n", 1)
        temp = sampling.get("temperature", self.config.temperature)
        if n > 1 and temp <= 0.0:
            raise ValueError("n > 1 requires temperature > 0 (greedy "
                             "choices would all be identical)")
        kwargs = dict(
            max_tokens=sampling.get("max_tokens"),
            temperature=sampling.get("temperature"),
            top_k=sampling["top_k"],
            adapter=sampling.get("adapter"),
            logit_bias=sampling.get("logit_bias"),
            guided=sampling.get("guided"),
            presence_penalty=sampling.get("presence_penalty", 0.0),
            frequency_penalty=sampling.get("frequency_penalty", 0.0),
            logprobs=sampling.get("logprobs"),
            stop=sampling.get("stop"))
        if n == 1:
            return [self._generate(prompt, **kwargs)]
        if kwargs.get("stop"):
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(max_workers=n) as pool:
                return list(pool.map(
                    lambda _: self._generate(prompt, **kwargs),
                    range(n)))
        from ray_tpu.util import tracing
        with tracing.span("engine_generate_n", component="llm.engine",
                          tags={"model": self.config.model_id,
                                "n": str(n)}):
            admitted = [self._make_request(
                prompt, max_tokens=kwargs["max_tokens"],
                temperature=kwargs["temperature"], top_k=kwargs["top_k"],
                adapter=kwargs["adapter"],
                logit_bias=kwargs["logit_bias"],
                guided=kwargs["guided"],
                presence_penalty=kwargs["presence_penalty"],
                frequency_penalty=kwargs["frequency_penalty"],
                logprobs=kwargs["logprobs"])
                for _ in range(n)]
            for _, r in admitted:
                while not r.done:
                    r.wait_done(timeout=1.0)
        results = []
        for ids, r in admitted:
            if r.error is not None:
                raise RuntimeError(r.error)
            out_ids = [i for i in r.output_ids if i not in r.stop_ids]
            result = {
                "text": self.tokenizer.decode(out_ids),
                "prompt_tokens": len(ids),
                "completion_tokens": len(r.output_ids),
                "finish_reason": r.finish_reason,
            }
            if r.logprobs is not None:
                result["logprob_data"] = [
                    e for i, e in zip(r.output_ids, r.logprob_data)
                    if i not in r.stop_ids]
            results.append(result)
        return results

    def register_adapter(self, name: str, lora_params) -> None:
        """Serve a LoRA adapter as an additional model id (reference:
        serve/llm multi-LoRA — requests select it via `model`)."""
        self.engine.register_adapter(name, lora_params)

    def _resolve_adapter(self, model: Optional[str]) -> Optional[str]:
        """Map the request's `model` onto a registered LoRA adapter;
        the base model_id (or absent) means no adapter."""
        if model is None or model == self.config.model_id:
            return None
        if model in self.engine._adapters:
            return model
        raise ValueError(
            f"unknown model {model!r}; available: "
            f"{[self.config.model_id, *self.engine._adapters]}")

    @staticmethod
    def _flatten_content(content: Any) -> str:
        """OpenAI message content is a string or a list of typed parts;
        flatten text parts rather than interpolating a Python repr."""
        if isinstance(content, str):
            return content
        if isinstance(content, list):
            texts = []
            for part in content:
                if not isinstance(part, dict) or part.get("type") != "text":
                    raise ValueError(
                        "only text content parts are supported")
                texts.append(str(part.get("text", "")))
            return "".join(texts)
        raise ValueError("message content must be a string or a list of "
                         "content parts")

    @staticmethod
    def _invalid_request(err: ValueError) -> Dict[str, Any]:
        return {"error": {"message": str(err),
                          "type": "invalid_request_error"}}

    def _generate(self, prompt: str, *, max_tokens: Optional[int] = None,
                  temperature: Optional[float] = None,
                  top_k: int = 0,
                  adapter: Optional[str] = None,
                  logit_bias: Optional[Dict[int, float]] = None,
                  guided=None,
                  presence_penalty: float = 0.0,
                  frequency_penalty: float = 0.0,
                  logprobs: Optional[int] = None,
                  stop: Optional[List[str]] = None
                  ) -> Dict[str, Any]:
        if stop:
            return self._generate_with_stop(
                prompt, max_tokens=max_tokens, temperature=temperature,
                top_k=top_k, adapter=adapter, logit_bias=logit_bias,
                guided=guided, presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty, logprobs=logprobs,
                stop=stop)
        from ray_tpu.util import tracing
        with tracing.span("engine_generate", component="llm.engine",
                          tags={"model": self.config.model_id}):
            ids, request = self._make_request(
                prompt, max_tokens=max_tokens, temperature=temperature,
                top_k=top_k, adapter=adapter, logit_bias=logit_bias,
                guided=guided, presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty, logprobs=logprobs)
            while not request.done:
                request.wait_done(timeout=1.0)
        if request.error is not None:
            raise RuntimeError(request.error)
        out_ids = [i for i in request.output_ids
                   if i not in request.stop_ids]
        result = {
            "text": self.tokenizer.decode(out_ids),
            "prompt_tokens": len(ids),
            "completion_tokens": len(request.output_ids),
            "finish_reason": request.finish_reason,
        }
        if request.logprobs is not None:
            result["logprob_data"] = [
                e for i, e in zip(request.output_ids,
                                  request.logprob_data)
                if i not in request.stop_ids]
        return result

    def _generate_with_stop(self, prompt: str, *,
                            max_tokens: Optional[int] = None,
                            temperature: Optional[float] = None,
                            top_k: int = 0,
                            adapter: Optional[str] = None,
                            logit_bias: Optional[Dict[int, float]] = None,
                            guided=None,
                            presence_penalty: float = 0.0,
                            frequency_penalty: float = 0.0,
                            logprobs: Optional[int] = None,
                            stop: List[str] = ()) -> Dict[str, Any]:
        """Non-streaming generation with OpenAI stop STRINGS: watch
        the decoded text incrementally and cancel the engine request
        at the first stop-sequence hit (the stop text itself is not
        returned), instead of decoding to max_tokens and truncating
        after the fact."""
        import queue

        from ray_tpu.util import tracing
        with tracing.span("engine_generate", component="llm.engine",
                          tags={"model": self.config.model_id}):
            ids, request = self._make_request(
                prompt, max_tokens=max_tokens, temperature=temperature,
                top_k=top_k, adapter=adapter, logit_bias=logit_bias,
                guided=guided, presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty, logprobs=logprobs,
                stream_queue=queue.Queue())
            text = ""
            hit = False
            for delta in stream_text_deltas(self.tokenizer, request):
                text += delta
                cuts = [text.find(s) for s in stop if s in text]
                if cuts:
                    text = text[:min(cuts)]
                    hit = True
                    self.engine.cancel(request, "stop")
                    break
        result = {
            "text": text,
            "prompt_tokens": len(ids),
            "completion_tokens": len(request.output_ids),
            "finish_reason": "stop" if hit else request.finish_reason,
        }
        if request.logprobs is not None:
            kept, acc = [], []
            for i, e in zip(request.output_ids, request.logprob_data):
                if i in request.stop_ids:
                    continue
                acc.append(i)
                kept.append(e)
                if hit and len(self.tokenizer.decode(acc)) >= len(text):
                    break  # logprobs stop where the returned text does
            result["logprob_data"] = kept
        return result

    def _generate_stream(self, prompt: str, *,
                         max_tokens: Optional[int] = None,
                         temperature: Optional[float] = None,
                         top_k: int = 0,
                         adapter: Optional[str] = None,
                         logit_bias: Optional[Dict[int, float]] = None,
                         guided=None,
                         presence_penalty: float = 0.0,
                         frequency_penalty: float = 0.0,
                         logprobs: Optional[int] = None,
                         stop: Optional[List[str]] = None,
                         request_sink: Optional[Dict[str, Any]] = None):
        """Yield decoded text per emitted token (reference: vLLM output
        streams behind serve token streaming). The engine's stepper
        pushes each token onto the request's queue as it decodes.
        With ``stop`` strings, a possible stop-prefix tail is held
        back so stop text is never streamed, and the engine request
        is cancelled at the hit."""
        import queue

        _ids, request = self._make_request(
            prompt, max_tokens=max_tokens, temperature=temperature,
            top_k=top_k, adapter=adapter, logit_bias=logit_bias,
            guided=guided, presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty, logprobs=logprobs,
            stream_queue=queue.Queue())
        if request_sink is not None:
            # exact usage for stream_options.include_usage: the caller
            # reads output_ids after the stream drains
            request_sink["request"] = request
            request_sink["prompt_tokens"] = len(_ids)
        deltas = stream_token_deltas(self.tokenizer, request)
        if not stop:
            yield from deltas
            return
        text = ""
        emitted = 0
        holdback = max(len(s) for s in stop) - 1
        for delta in deltas:
            text += delta
            cuts = [text.find(s) for s in stop if s in text]
            if cuts:
                cut = min(cuts)
                if cut > emitted:
                    yield text[emitted:cut]
                self.engine.cancel(request, "stop")
                return
            safe = len(text) - holdback
            if safe > emitted:
                yield text[emitted:safe]
                emitted = safe
        if len(text) > emitted:
            yield text[emitted:]

    # -- OpenAI-compatible surface (routed by path) --------------------
    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        path = request.get("__path__", "")
        if path.endswith("/chat/completions"):
            return self.chat_completions(request)
        if path.endswith("/completions"):
            return self.completions(request)
        if path.endswith("/embeddings"):
            return self.embeddings(request)
        if path.endswith("/score"):
            return self.score(request)
        if path.endswith("/models"):
            return {"object": "list",
                    "data": [{"id": self.config.model_id,
                              "object": "model"}]}
        if path.endswith("/stats"):
            return self.engine.stats()
        return {"error": f"unknown route {path!r}"}

    def embeddings(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI /v1/embeddings: mean-pooled final hidden states
        (reference: serve/llm embedding model support via vLLM)."""
        raw = body.get("input", "")
        if isinstance(raw, str):
            inputs = [raw]
        elif isinstance(raw, (list, tuple)):
            inputs = list(raw)
        else:
            return self._invalid_request(ValueError(
                "input must be a string or a list of strings"))
        if not inputs or not all(isinstance(t, str) and t
                                 for t in inputs):
            return self._invalid_request(ValueError(
                "input must be a non-empty string or list of them"))
        limit = self.config.engine.max_seq
        data = []
        total = 0
        for i, text in enumerate(inputs):
            ids = self.tokenizer.encode(text)
            if len(ids) > limit:
                # OpenAI returns a context-length error here; silent
                # tail-truncation would hand back an embedding of the
                # document's end labeled as the whole document
                return self._invalid_request(ValueError(
                    f"input {i} is {len(ids)} tokens; this model's "
                    f"maximum context is {limit}"))
            total += len(ids)
            vec = self.engine.embed(ids)
            data.append({"object": "embedding", "index": i,
                         "embedding": [float(x) for x in vec]})
        return {
            "object": "list",
            "model": body.get("model", self.config.model_id),
            "data": data,
            "usage": {"prompt_tokens": total, "total_tokens": total},
        }

    def _token_str(self, tid: int) -> str:
        return self.tokenizer.decode([tid])

    def _completions_logprobs(self, r: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI completions logprobs object (tokens/token_logprobs/
        top_logprobs/text_offset)."""
        data = r["logprob_data"]
        tokens, lps, tops, offsets = [], [], [], []
        off = 0
        for e in data:
            ts = self._token_str(e["id"])
            tokens.append(ts)
            lps.append(e["logprob"])
            tops.append({self._token_str(tid): lp
                         for tid, lp in e["top"]})
            offsets.append(off)
            off += len(ts)
        return {"tokens": tokens, "token_logprobs": lps,
                "top_logprobs": tops, "text_offset": offsets}

    def _chat_logprobs(self, r: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI chat logprobs object (content[].top_logprobs)."""
        content = []
        for e in r["logprob_data"]:
            ts = self._token_str(e["id"])
            content.append({
                "token": ts,
                "logprob": e["logprob"],
                "bytes": list(ts.encode()),
                "top_logprobs": [
                    {"token": self._token_str(tid), "logprob": lp,
                     "bytes": list(self._token_str(tid).encode())}
                    for tid, lp in e["top"]],
            })
        return {"content": content}

    def score(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """/v1/score: similarity of text_1 against each text_2
        (reference surface: openai_api_models.py:123 ScoreRequest via
        vLLM). Cross-encoder models are not in-tree, so the score is
        the cosine similarity of the engine's pooled embeddings —
        stated divergence; same request/response shape."""
        t1 = body.get("text_1", body.get("query"))
        t2 = body.get("text_2", body.get("documents"))
        if not isinstance(t1, str) or not t1:
            return self._invalid_request(ValueError(
                "text_1 must be a non-empty string"))
        if isinstance(t2, str):
            texts = [t2]
        elif isinstance(t2, (list, tuple)):
            texts = list(t2)
        else:
            return self._invalid_request(ValueError(
                "text_2 must be a string or a list of strings"))
        if not texts or not all(isinstance(t, str) and t for t in texts):
            return self._invalid_request(ValueError(
                "text_2 must be a non-empty string or list of them"))
        limit = self.config.engine.max_seq
        ids1 = self.tokenizer.encode(t1)
        if len(ids1) > limit:
            return self._invalid_request(ValueError(
                f"text_1 is {len(ids1)} tokens; this model's maximum "
                f"context is {limit}"))
        import numpy as _np
        q = self.engine.embed(ids1)
        qn = q / max(float(_np.linalg.norm(q)), 1e-12)
        total = len(ids1)
        data = []
        for i, text in enumerate(texts):
            ids = self.tokenizer.encode(text)
            if len(ids) > limit:
                return self._invalid_request(ValueError(
                    f"text_2[{i}] is {len(ids)} tokens; this model's "
                    f"maximum context is {limit}"))
            total += len(ids)
            d = self.engine.embed(ids)
            dn = d / max(float(_np.linalg.norm(d)), 1e-12)
            data.append({"object": "score", "index": i,
                         "score": float(qn @ dn)})
        return {
            "object": "list",
            "model": body.get("model", self.config.model_id),
            "data": data,
            "usage": {"prompt_tokens": total, "total_tokens": total},
        }

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = body.get("prompt", "")
        if not isinstance(prompt, str):
            return self._invalid_request(ValueError("prompt must be a string"))
        try:
            sampling = self._validate_sampling(body)
            # response_format works on completions too (the reference's
            # vLLM request models carry it on both surfaces); tools are
            # chat-only
            guided_info = self._resolve_guided(body, allow_tools=False)
        except ValueError as e:
            return self._invalid_request(e)
        sampling["guided"] = guided_info["constraint"]
        if body.get("stream"):
            if sampling.get("n", 1) > 1:
                return self._invalid_request(ValueError(
                    "n > 1 is not supported with stream=true"))
            if sampling.get("logprobs") is not None:
                return self._invalid_request(ValueError(
                    "logprobs are not supported with stream=true"))
            return self._stream_completions(body, prompt, sampling)
        try:
            results = self._generate_n(prompt, sampling)
        except ValueError as e:
            return self._invalid_request(e)
        result = results[0]
        choices = []
        for i, r in enumerate(results):
            choice = {"index": i, "text": r["text"],
                      "finish_reason": r["finish_reason"]}
            if r.get("logprob_data") is not None:
                choice["logprobs"] = self._completions_logprobs(r)
            choices.append(choice)
        return {
            "id": f"cmpl-{uuid.uuid4().hex[:24]}",
            "object": "text_completion",
            "model": body.get("model", self.config.model_id),
            "choices": choices,
            "usage": {
                "prompt_tokens": result["prompt_tokens"],
                "completion_tokens": sum(r["completion_tokens"]
                                         for r in results),
                "total_tokens": (result["prompt_tokens"]
                                 + sum(r["completion_tokens"]
                                       for r in results)),
            },
        }

    def _stream_completions(self, body: Dict[str, Any], prompt: str,
                            sampling: Dict[str, Any]):
        """SSE generator for /v1/completions with stream=true
        (reference: OpenAI SSE chunks, serve/llm streaming responses)."""
        import json as _json

        cmpl_id = f"cmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", self.config.model_id)
        sink: Dict[str, Any] = {}
        for text in self._generate_stream(
                prompt, max_tokens=sampling.get("max_tokens"),
                temperature=sampling.get("temperature"),
                top_k=sampling["top_k"],
                adapter=sampling.get("adapter"),
                logit_bias=sampling.get("logit_bias"),
                guided=sampling.get("guided"),
                presence_penalty=sampling.get("presence_penalty", 0.0),
                frequency_penalty=sampling.get("frequency_penalty", 0.0),
                request_sink=sink,
                stop=sampling.get("stop")):
            chunk = {"id": cmpl_id, "object": "text_completion",
                     "model": model,
                     "choices": [{"index": 0, "text": text,
                                  "finish_reason": None}]}
            yield f"data: {_json.dumps(chunk)}\n\n"
        final = {"id": cmpl_id, "object": "text_completion", "model": model,
                 "choices": [{"index": 0, "text": "",
                              "finish_reason": "stop"}]}
        yield f"data: {_json.dumps(final)}\n\n"
        if sampling.get("include_usage"):
            yield self._usage_chunk(sink, cmpl_id, "text_completion",
                                    model)
        yield "data: [DONE]\n\n"

    @staticmethod
    def _usage_chunk(sink: Dict[str, Any], oid: str, obj: str,
                     model: str) -> str:
        """stream_options.include_usage: the final usage-only SSE
        chunk (choices: []) shared by both streaming endpoints."""
        pt = sink.get("prompt_tokens", 0)
        ct = len(sink["request"].output_ids) if "request" in sink else 0
        payload = {"id": oid, "object": obj, "model": model,
                   "choices": [],
                   "usage": {"prompt_tokens": pt,
                             "completion_tokens": ct,
                             "total_tokens": pt + ct}}
        return f"data: {json.dumps(payload)}\n\n"

    def _stream_chat(self, body: Dict[str, Any], prompt: str,
                     sampling: Dict[str, Any],
                     guided_info: Optional[Dict[str, Any]] = None):
        chat_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", self.config.model_id)

        def chunk(delta, finish=None):
            payload = {"id": chat_id, "object": "chat.completion.chunk",
                       "model": model,
                       "choices": [{"index": 0, "delta": delta,
                                    "finish_reason": finish}]}
            return f"data: {json.dumps(payload)}\n\n"

        yield chunk({"role": "assistant"})
        sink: Dict[str, Any] = {}
        deltas = self._generate_stream(
            prompt, max_tokens=sampling.get("max_tokens"),
            temperature=sampling.get("temperature"),
            top_k=sampling["top_k"],
            adapter=sampling.get("adapter"),
            logit_bias=sampling.get("logit_bias"),
            guided=sampling.get("guided"),
            presence_penalty=sampling.get("presence_penalty", 0.0),
            frequency_penalty=sampling.get("frequency_penalty", 0.0),
            logprobs=sampling.get("logprobs"),
            request_sink=sink,
            stop=sampling.get("stop"))
        tools_live = guided_info and guided_info["tool_mode"] is not None
        def usage_chunk():
            if not sampling.get("include_usage"):
                return None
            return self._usage_chunk(sink, chat_id,
                                     "chat.completion.chunk", model)

        if not tools_live:
            for text in deltas:
                yield chunk({"content": text})
            yield chunk({}, finish="stop")
            uc = usage_chunk()
            if uc:
                yield uc
            yield "data: [DONE]\n\n"
            return
        # tool-call streaming (OpenAI delta.tool_calls): the first
        # event carries id + function name; argument JSON streams
        # incrementally as it decodes
        made_tool = False
        for kind, val in self._stream_tool_events(
                deltas, guided_info["tool_names"]):
            if kind == "content":
                yield chunk({"content": val})
            elif kind == "tool_head":
                made_tool = True
                yield chunk({"tool_calls": [{
                    "index": 0,
                    "id": f"call_{uuid.uuid4().hex[:24]}",
                    "type": "function",
                    "function": {"name": val, "arguments": ""}}]})
            else:
                yield chunk({"tool_calls": [{
                    "index": 0,
                    "function": {"arguments": val}}]})
        yield chunk({}, finish="tool_calls" if made_tool else "stop")
        uc = usage_chunk()
        if uc:
            yield uc
        yield "data: [DONE]\n\n"

    def chat_completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        messages = body.get("messages", [])
        if not isinstance(messages, list) or any(
                not isinstance(m, dict) for m in messages):
            return self._invalid_request(
                ValueError("messages must be a list of objects"))
        try:
            sampling = self._validate_sampling(body)
            guided_info = self._resolve_guided(body)
            prompt = self._chat_prompt(body, messages)
        except ValueError as e:
            return self._invalid_request(e)
        sampling["guided"] = guided_info["constraint"]
        if body.get("stream"):
            if sampling.get("n", 1) > 1:
                return self._invalid_request(ValueError(
                    "n > 1 is not supported with stream=true"))
            if sampling.get("logprobs") is not None:
                return self._invalid_request(ValueError(
                    "logprobs are not supported with stream=true"))
            return self._stream_chat(body, prompt, sampling, guided_info)
        try:
            results = self._generate_n(prompt, sampling)
        except ValueError as e:
            return self._invalid_request(e)
        result = results[0]
        choices = []
        for i, r in enumerate(results):
            message, finish = self._chat_message(guided_info, r)
            choice = {"index": i, "message": message,
                      "finish_reason": finish}
            if r.get("logprob_data") is not None:
                choice["logprobs"] = self._chat_logprobs(r)
            choices.append(choice)
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "model": body.get("model", self.config.model_id),
            "choices": choices,
            "usage": {
                "prompt_tokens": result["prompt_tokens"],
                "completion_tokens": sum(r["completion_tokens"]
                                         for r in results),
                "total_tokens": (result["prompt_tokens"]
                                 + sum(r["completion_tokens"]
                                       for r in results)),
            },
        }


class MultiplexLLMServer:
    """One deployment serving MANY models: requests route by the OpenAI
    ``model`` field to a per-replica LRU of resident LLMServer engines
    via @serve.multiplexed; unknown ids get a 404 model_not_found and
    per-model request/token counters feed /metrics (reference:
    serve/llm/__init__.py:178 multi-model build_openai_app +
    _internal/serve routing by model id)."""

    def __init__(self, configs: List[LLMConfig],
                 params_blobs: Optional[Dict[str, bytes]] = None,
                 max_models_per_replica: int = 2):
        from ray_tpu.util import metrics as metrics_mod
        if not configs:
            raise ValueError("MultiplexLLMServer needs >= 1 LLMConfig")
        self._configs: Dict[str, LLMConfig] = {}
        for c in configs:
            if c.model_id in self._configs:
                raise ValueError(f"duplicate model_id {c.model_id!r}")
            self._configs[c.model_id] = c
        self._params = dict(params_blobs or {})
        # Wire the instance's LRU size through @serve.multiplexed at
        # init time (the decorator binds max_num_models_per_replica at
        # decoration; replicas construct this class locally, so the
        # bound loader never needs to pickle).
        loader = serve.multiplexed(
            max_num_models_per_replica=max_models_per_replica)(
                MultiplexLLMServer._load_model)
        self._load = lambda mid: loader(self, mid)
        self._requests = metrics_mod.Counter(
            "ray_tpu_serve_llm_requests_total", "LLM requests by model",
            tag_keys=("model",))
        self._tokens = metrics_mod.Counter(
            "ray_tpu_serve_llm_generated_tokens_total",
            "Generated tokens by model", tag_keys=("model",))

    def _load_model(self, model_id: str) -> LLMServer:
        return LLMServer(self._configs[model_id],
                         self._params.get(model_id))

    def _resolve(self, body: Dict[str, Any]):
        """model id -> resident LLMServer, or a 404 error dict."""
        model = body.get("model")
        if model is None and len(self._configs) == 1:
            model = next(iter(self._configs))
        if model not in self._configs:
            return None, {
                "__status__": 404,
                "error": {
                    "message": f"model {model!r} not found; serving "
                               f"{sorted(self._configs)}",
                    "type": "invalid_request_error",
                    "code": "model_not_found"}}
        self._requests.inc(tags={"model": model})
        return self._load(model), None

    def _count_tokens(self, model: str, result_or_n) -> None:
        n = (result_or_n if isinstance(result_or_n, (int, float))
             else result_or_n.get("completion_tokens", 0))
        if n:
            self._tokens.inc(n, tags={"model": model})

    def __call__(self, request: Dict[str, Any]) -> Any:
        path = request.get("__path__", "")
        if path.endswith("/models"):
            return {"object": "list",
                    "data": [{"id": mid, "object": "model"}
                             for mid in self._configs]}
        server, err = self._resolve(request)
        if err is not None:
            return err
        out = server(request)
        # count completion tokens for non-streaming responses; the
        # streaming paths count per-chunk inside the wrapped generator
        if isinstance(out, dict):
            usage = out.get("usage") or {}
            self._count_tokens(request.get("model")
                               or server.config.model_id,
                               usage.get("completion_tokens", 0))
            return out
        if hasattr(out, "__iter__") and not isinstance(out, (str, bytes)):
            model = request.get("model") or server.config.model_id

            def counted():
                n = 0
                for chunk in out:
                    n += 1
                    yield chunk
                self._count_tokens(model, n)
            return counted()
        return out


def build_llm_deployment(config: LLMConfig, params=None,
                         name: Optional[str] = None):
    """An Application serving `config` (reference:
    serve/llm build_llm_deployment)."""
    params_blob = None
    if params is not None:
        from ray_tpu.core import serialization
        params_blob = serialization.dumps(params)
    dep = serve.deployment(
        LLMServer,
        name=name or config.model_id,
        num_replicas=config.num_replicas,
        max_ongoing_requests=config.max_ongoing_requests,
        request_router=("prefix_aware" if config.prefix_routing
                        else "pow2"))
    return dep.bind(config, params_blob)


def build_openai_app(llm_configs: List[LLMConfig] = None, *,
                     config: LLMConfig = None, params=None,
                     params_by_model: Optional[Dict[str, Any]] = None,
                     name: str = "openai-llm",
                     max_models_per_replica: int = 2):
    """OpenAI-compatible app (reference: serve/llm/__init__.py:178
    build_openai_app serving many models per app with model-id routing).

    One config -> a plain LLMServer deployment (no routing layer).
    Many configs -> a MultiplexLLMServer whose replicas keep an LRU of
    resident engines and route by the request ``model`` field; unknown
    ids answer 404 model_not_found, /v1/models lists all ids, and
    per-model request/token counters land in /metrics.
    """
    if config is not None:
        return build_llm_deployment(config, params=params)
    configs = llm_configs or [LLMConfig()]
    if len(configs) == 1 and params_by_model is None:
        return build_llm_deployment(configs[0], params=params)
    if params is not None:
        raise ValueError(
            "multi-model apps take params_by_model={model_id: params}, "
            "not params= (which model would it apply to?)")
    from ray_tpu.core import serialization
    blobs = {mid: serialization.dumps(p)
             for mid, p in (params_by_model or {}).items()}
    dep = serve.deployment(
        MultiplexLLMServer, name=name,
        num_replicas=max(c.num_replicas for c in configs),
        max_ongoing_requests=max(c.max_ongoing_requests
                                 for c in configs),
        request_router=("prefix_aware"
                        if any(c.prefix_routing for c in configs)
                        else "pow2"))
    return dep.bind(configs, blobs, max_models_per_replica)
