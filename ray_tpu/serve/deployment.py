"""Deployments and applications: the declarative serve API.

Capability parity with the reference's API layer (reference:
python/ray/serve/api.py @serve.deployment / serve.run:694;
deployment.py Deployment.options/bind; model composition via bound
applications resolving to DeploymentHandles).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig


class Application:
    """A bound deployment graph node (reference: serve's built
    Application). ``Deployment.bind(*args)`` captures init args; nested
    Applications become DeploymentHandles at deploy time."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class: Any, name: str,
                 config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                max_queued_requests: Optional[int] = None,
                shed_queue_wait_s: Optional[float] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                user_config: Optional[Dict[str, Any]] = None,
                request_router: Optional[str] = None,
                ) -> "Deployment":
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if shed_queue_wait_s is not None:
            cfg.shed_queue_wait_s = shed_queue_wait_s
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = dict(user_config)
        if request_router is not None:
            cfg.request_router = request_router
        return Deployment(self.func_or_class, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 100,
               max_queued_requests: int = -1,
               shed_queue_wait_s: float = 0.0,
               autoscaling_config=None, ray_actor_options=None,
               user_config=None, request_router: str = "pow2"):
    """``@serve.deployment`` (reference: python/ray/serve/api.py)."""

    def make(target) -> Deployment:
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            shed_queue_wait_s=shed_queue_wait_s,
            ray_actor_options=dict(ray_actor_options or {}),
            user_config=user_config,
            request_router=request_router)
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict)
                else autoscaling_config)
        return Deployment(target, name or target.__name__, cfg)

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


def flatten_application(app: Application, app_name: str,
                        route_prefix: Optional[str]) -> List[dict]:
    """Depth-first walk of the bound graph → controller deploy specs.
    Bound child Applications are replaced with DeploymentHandles.
    The root deployment gets the route_prefix (ingress)."""
    from ray_tpu.serve.handle import DeploymentHandle

    specs: Dict[str, dict] = {}

    def visit(node: Application) -> DeploymentHandle:
        dep = node.deployment
        resolved_args = tuple(
            visit(a) if isinstance(a, Application) else a
            for a in node.args)
        resolved_kwargs = {
            k: (visit(v) if isinstance(v, Application) else v)
            for k, v in node.kwargs.items()}
        if dep.name not in specs:
            specs[dep.name] = {
                "name": dep.name,
                "callable_blob": serialization.dumps(dep.func_or_class),
                "init_args_blob": serialization.dumps(
                    (resolved_args, resolved_kwargs)),
                "config": dep.config,
                "route_prefix": None,
            }
        return DeploymentHandle(dep.name, app_name)

    visit(app)
    specs[app.deployment.name]["route_prefix"] = route_prefix
    return list(specs.values())
