"""Declarative serve config: schema'd YAML/dict application deploys.

Capability parity with the reference's config-file deploy surface
(reference: python/ray/serve/schema.py:431 ServeDeploySchema +
serve/scripts.py `serve deploy` — applications declared as import
paths with per-deployment overrides, applied idempotently). The same
dict shape drives the CLI (`ray-tpu serve deploy config.yaml`), the
dashboard REST endpoint, and `serve.deploy_config()`.

    applications:
      - name: app1
        route_prefix: /a
        import_path: my_module:app        # Application or builder fn
        args: {model: "m1"}               # passed to a builder fn
        deployments:
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 16
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_DEPLOYMENT_FIELDS = ("name", "num_replicas", "max_ongoing_requests",
                      "max_queued_requests", "shed_queue_wait_s",
                      "autoscaling_config", "ray_actor_options",
                      "user_config")
_APP_FIELDS = ("name", "import_path", "route_prefix", "args",
               "runtime_env", "deployments")


@dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    max_queued_requests: Optional[int] = None
    shed_queue_wait_s: Optional[float] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    user_config: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        unknown = set(d) - set(_DEPLOYMENT_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown deployment config field(s) {sorted(unknown)}; "
                f"supported: {_DEPLOYMENT_FIELDS}")
        if "name" not in d:
            raise ValueError("deployment override requires 'name'")
        return cls(**d)

    def overrides(self) -> Dict[str, Any]:
        out = {}
        for key in _DEPLOYMENT_FIELDS[1:]:
            value = getattr(self, key)
            if value is not None:
                out[key] = value
        return out


@dataclass
class ServeApplicationSchema:
    name: str
    import_path: str
    route_prefix: Optional[str] = "/"
    args: Dict[str, Any] = field(default_factory=dict)
    runtime_env: Optional[Dict[str, Any]] = None
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        unknown = set(d) - set(_APP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown application config field(s) {sorted(unknown)}; "
                f"supported: {_APP_FIELDS}")
        for required in ("name", "import_path"):
            if required not in d:
                raise ValueError(f"application config requires {required!r}")
        if ":" not in d["import_path"]:
            raise ValueError(
                "import_path must look like 'module.sub:attribute', got "
                f"{d['import_path']!r}")
        deployments = [DeploymentSchema.from_dict(dd)
                       for dd in d.get("deployments", ())]
        return cls(name=d["name"], import_path=d["import_path"],
                   route_prefix=d.get("route_prefix", "/"),
                   args=dict(d.get("args") or {}),
                   runtime_env=d.get("runtime_env"),
                   deployments=deployments)


@dataclass
class ServeDeploySchema:
    applications: List[ServeApplicationSchema]
    http_options: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        unknown = set(d) - {"applications", "http_options"}
        if unknown:
            raise ValueError(
                f"unknown top-level config field(s) {sorted(unknown)}")
        apps = d.get("applications")
        if not isinstance(apps, list) or not apps:
            raise ValueError("config requires a non-empty 'applications' "
                             "list")
        names = [a.get("name") for a in apps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate application names in {names}")
        http_options = d.get("http_options")
        if http_options is not None and not isinstance(http_options, dict):
            raise ValueError("http_options must be a dict (host/port)")
        return cls(applications=[ServeApplicationSchema.from_dict(a)
                                 for a in apps],
                   http_options=http_options)


def _import_target(import_path: str):
    module_name, _, attr = import_path.partition(":")
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _map_deployments(app, transform):
    """Rebuild a bound Application graph with ``transform(deployment)``
    applied to every node (the one graph-walk shape shared by override
    and runtime_env application)."""
    from ray_tpu.serve.deployment import Application

    def visit(node: Application) -> Application:
        dep = transform(node.deployment)
        args = tuple(visit(a) if isinstance(a, Application) else a
                     for a in node.args)
        kwargs = {k: (visit(v) if isinstance(v, Application) else v)
                  for k, v in node.kwargs.items()}
        return Application(dep, args, kwargs)

    return visit(app)


def _apply_overrides(app, overrides: Dict[str, Dict[str, Any]]):
    """Per-deployment option overrides by name (reference: schema.py
    deployment overrides merged over the code-declared options)."""
    applied = set()

    def transform(dep):
        if dep.name in overrides:
            applied.add(dep.name)
            return dep.options(**overrides[dep.name])
        return dep

    out = _map_deployments(app, transform)
    missing = set(overrides) - applied
    if missing:
        raise ValueError(
            f"deployment override(s) {sorted(missing)} match no "
            "deployment in the application graph")
    return out


def build_app_from_schema(schema: ServeApplicationSchema):
    """import_path -> a bound Application with overrides applied."""
    from ray_tpu.serve.deployment import Application

    target = _import_target(schema.import_path)
    if isinstance(target, Application):
        if schema.args:
            raise ValueError(
                f"{schema.import_path} is a bound Application; 'args' "
                "requires a builder function")
        app = target
    elif callable(target):
        app = target(**schema.args)
        if not isinstance(app, Application):
            raise TypeError(
                f"{schema.import_path} returned {type(app).__name__}, "
                "expected a bound Application")
    else:
        raise TypeError(f"{schema.import_path} is neither an "
                        "Application nor a builder callable")
    overrides = {d.name: d.overrides() for d in schema.deployments
                 if d.overrides()}
    if overrides:
        app = _apply_overrides(app, overrides)
    if schema.runtime_env:
        app = _apply_runtime_env(app, schema.runtime_env)
    return app


def _apply_runtime_env(app, runtime_env: Dict[str, Any]):
    """Application-level runtime_env: every replica actor inherits it
    via ray_actor_options unless a deployment set its own (reference:
    ServeApplicationSchema.runtime_env applied per deployment)."""

    def transform(dep):
        opts = dict(dep.config.ray_actor_options)
        if "runtime_env" in opts:
            return dep
        opts["runtime_env"] = dict(runtime_env)
        return dep.options(ray_actor_options=opts)

    return _map_deployments(app, transform)


def deploy_config(config: Dict[str, Any]) -> List[str]:
    """Apply a declarative config: deploy every application; returns
    the deployed application names (reference: serve deploy + REST
    PUT /api/serve/applications)."""
    from ray_tpu import serve

    schema = ServeDeploySchema.from_dict(config)
    if schema.http_options:
        # Start the proxy with the declared host/port (no-op when one
        # is already running — the first deploy wins the bind).
        serve.start(proxy=True,
                    http_options=serve.HTTPOptions(**schema.http_options))
    deployed = []
    for app_schema in schema.applications:
        app = build_app_from_schema(app_schema)
        serve.run(app, name=app_schema.name,
                  route_prefix=app_schema.route_prefix)
        deployed.append(app_schema.name)
    return deployed


def deploy_config_file(path: str) -> List[str]:
    import yaml
    with open(path) as f:
        config = yaml.safe_load(f)
    return deploy_config(config)
