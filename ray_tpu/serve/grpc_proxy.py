"""gRPC proxy: a second ingress next to the HTTP proxy.

Capability parity with the reference's gRPC proxy (reference:
python/ray/serve/_private/proxy.py:530 gRPCProxy — gRPC services whose
method handlers route into deployments, application selected via
request metadata). Implemented with grpc's GENERIC handlers, so no
protoc codegen is required: any fully-qualified method
``/pkg.Service/Method`` is accepted, payloads are JSON bytes, and the
target deployment resolves exactly like the HTTP proxy's routes.

Routing contract:
  - metadata ``route``: the route prefix to match (default "/") — the
    same longest-prefix table the HTTP proxy uses.
  - the request dict the deployment receives carries ``__method__``
    (the bare gRPC method name) and, when metadata ``path`` is set,
    ``__path__`` (sub-path routing, e.g. the OpenAI surface).
  - methods whose name ends in ``Stream`` are served as
    server-streaming (one JSON message per streamed chunk); everything
    else is unary. Replica streaming into a unary method is collected
    into a list.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.admission import BackpressureError
from ray_tpu.serve.proxy import _ProxyState


def _to_bytes(chunk: Any) -> bytes:
    if isinstance(chunk, (bytes, bytearray)):
        return bytes(chunk)
    if isinstance(chunk, str):
        return chunk.encode()
    return json.dumps(chunk).encode()


class _GenericHandler:
    def __init__(self, state: _ProxyState):
        self.state = state

    def _resolve(self, metadata: Dict[str, str]):
        route = metadata.get("route", "/")
        match = self.state.match(route)
        if match is None:
            self.state.refresh()
            match = self.state.match(route)
        return match

    def _build_request(self, request_bytes: bytes, method_name: str,
                       metadata: Dict[str, str]) -> Dict[str, Any]:
        request: Dict[str, Any] = {}
        if request_bytes:
            parsed = json.loads(request_bytes.decode())
            if not isinstance(parsed, dict):
                raise ValueError("request payload must be a JSON object")
            request.update(parsed)
        request.pop("__method__", None)
        request.pop("__path__", None)
        request["__method__"] = method_name
        if metadata.get("path"):
            request["__path__"] = metadata["path"]
        return request

    def _stream(self, dep: str, request: Dict[str, Any]):
        from ray_tpu.core import serialization
        from ray_tpu.serve.handle import _get_router
        router = _get_router(dep, self.state.controller)
        blob = serialization.dumps(((request,), {}))
        return router.stream("__call__", blob, item_timeout_s=60.0)

    def unary(self, method_name: str):
        import grpc

        def handler(request_bytes, context):
            metadata = dict(context.invocation_metadata())
            match = self._resolve(metadata)
            if match is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no route {metadata.get('route', '/')!r}")
            dep, _rest = match
            try:
                request = self._build_request(request_bytes, method_name,
                                              metadata)
                gen = self._stream(dep, request)
                first = next(gen, None)
                if first is None:
                    return b"null"
                kind, value = first
                if kind == "single":
                    return _to_bytes(value)
                # replica streamed into a unary method: collect
                chunks = [value] + [chunk for _k, chunk in gen]
                return _to_bytes(chunks)
            except BackpressureError as exc:
                context.set_trailing_metadata(
                    (("retry-after-s", f"{exc.retry_after_s:.3f}"),))
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              str(exc))
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            except Exception as exc:  # noqa: BLE001 — surface as error
                context.abort(grpc.StatusCode.INTERNAL, str(exc))

        return handler

    def streaming(self, method_name: str):
        import grpc

        def handler(request_bytes, context):
            metadata = dict(context.invocation_metadata())
            match = self._resolve(metadata)
            if match is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no route {metadata.get('route', '/')!r}")
            dep, _rest = match
            try:
                request = self._build_request(request_bytes, method_name,
                                              metadata)
                for _kind, chunk in self._stream(dep, request):
                    yield _to_bytes(chunk)
            except BackpressureError as exc:
                context.set_trailing_metadata(
                    (("retry-after-s", f"{exc.retry_after_s:.3f}"),))
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              str(exc))
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            except Exception as exc:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, str(exc))

        return handler


class GrpcProxy:
    """Serves any ``/pkg.Service/Method`` via generic handlers."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 16):
        import grpc
        from concurrent import futures

        self.state = _ProxyState(controller)
        generic = _GenericHandler(self.state)

        class Router(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method.rsplit("/", 1)[-1]
                if method.endswith("Stream"):
                    return grpc.unary_stream_rpc_method_handler(
                        generic.streaming(method))
                return grpc.unary_unary_rpc_method_handler(
                    generic.unary(method))

        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self.server.add_generic_rpc_handlers((Router(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"could not bind gRPC proxy on {host}:{port}")
        self.server.start()

    def stop(self) -> None:
        self.server.stop(grace=0.5)
