"""@serve.multiplexed — per-replica LRU of loaded models.

Capability parity with the reference's model multiplexing (reference:
python/ray/serve/multiplex.py _ModelMultiplexWrapper — a replica holds up
to max_num_models_per_replica loaded models; requests carry a model id;
the loader runs on miss and the least-recently-used model is evicted).
"""

from __future__ import annotations

import collections
import functools
import threading

from ray_tpu.devtools import locktrace
from typing import Any, Callable, Optional

_current_model_id = threading.local()


def get_multiplexed_model_id() -> str:
    """Inside a multiplexed request: the model id being served
    (reference: serve.get_multiplexed_model_id)."""
    return getattr(_current_model_id, "value", "")


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator over ``async/sync def load_model(self, model_id)``; the
    wrapped callable becomes ``loader(model_id) -> model`` with LRU
    caching per replica."""

    def make(load_fn):
        @functools.wraps(load_fn)
        def wrapper(*args):
            from ray_tpu.serve import multiplex as _m
            if len(args) == 2:
                owner, model_id = args
                key = (id(wrapper), id(owner))
                call = lambda mid: load_fn(owner, mid)  # noqa: E731
            else:
                (model_id,) = args
                key, call = (id(wrapper), None), load_fn
            return _m._lookup(key, call, model_id,
                              max_num_models_per_replica)

        return wrapper

    if _fn is not None:
        return make(_fn)
    return make


# Cache state lives outside wrapper closures, reached via in-body import,
# so decorated classes stay picklable (see ray_tpu/serve/batching.py).
_state_lock = locktrace.traced_lock("serve.multiplex.state")
_caches: dict = {}


def _lookup(key, call, model_id, max_models):
    with _state_lock:
        cache = _caches.setdefault(key, collections.OrderedDict())
        if model_id in cache:
            cache.move_to_end(model_id)
            _current_model_id.value = model_id
            return cache[model_id]
    model = call(model_id)
    evicted = []
    with _state_lock:
        existing = cache.get(model_id)
        if existing is not None:
            # Concurrent miss: another thread loaded first — its model
            # is canonical; release ours instead of silently replacing
            # (the loser would leak its engine + stepper thread).
            evicted.append(model)
            model = existing
            cache.move_to_end(model_id)
        else:
            cache[model_id] = model
            cache.move_to_end(model_id)
            while len(cache) > max_models:
                evicted.append(cache.popitem(last=False)[1])
    # Release evicted models' resources outside the lock (an LLM model
    # holds an engine + stepper thread; reference: serve multiplex
    # calls the model's __del__ on eviction).
    for old in evicted:
        stop = getattr(old, "stop", None)
        if callable(stop):
            try:
                stop()
            except Exception:  # graftlint: disable=GL004
                pass  # eviction is best-effort; model is unreferenced
    _current_model_id.value = model_id
    return model
