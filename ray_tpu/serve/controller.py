"""Serve controller: declarative target-state reconciliation.

Capability parity with the reference's control plane (reference:
python/ray/serve/_private/controller.py:102 ServeController actor;
deployment_state.py:1713,2957 DeploymentState(Manager) reconciler;
autoscaling_state.py + serve/autoscaling_policy.py target-ongoing-
requests autoscaling; long_poll.py:228 LongPollHost config push).

Runs as an actor with a background reconcile thread; routers learn of
replica-set changes through versioned polls (the long-poll equivalent).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core import events
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentState:
    def __init__(self, name: str, app_name: str, callable_blob: bytes,
                 init_args_blob: bytes, config: DeploymentConfig,
                 route_prefix: Optional[str]):
        self.name = name
        self.app_name = app_name
        self.callable_blob = callable_blob
        self.init_args_blob = init_args_blob
        self.config = config
        self.route_prefix = route_prefix
        self.replicas: Dict[str, Any] = {}  # replica_id -> actor handle
        self.target = (config.autoscaling_config.min_replicas
                       if config.autoscaling_config
                       else config.num_replicas)
        self.next_replica_no = 0
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.status = "UPDATING"
        # stateful autoscaling policy instance (ray_tpu/autoscaler/
        # policy.py), created lazily per the config's policy name
        self.policy = None
        self.policy_name = None
        # latest router-pushed admission stats: (recv_monotonic, dict)
        self.slo_stats = None


class ServeController:
    """The singleton controller actor (named CONTROLLER_NAME)."""

    def __init__(self, reconcile_interval_s: float = 0.2):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        self._version = 0
        self._version_cv = threading.Condition(self._lock)
        self._stop_event = threading.Event()
        self._interval = reconcile_interval_s
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True)
        self._thread.start()

    # -- API (called by serve.run / handles / proxy) --

    def deploy_application(self, app_name: str,
                           deployments: List[dict]) -> None:
        """deployments: [{name, callable_blob, init_args_blob, config,
        route_prefix}] — full target state for the app (reference:
        application_state.py apply_deployment_args)."""
        with self._lock:
            keep = set()
            for d in deployments:
                name = d["name"]
                keep.add(name)
                existing = self._deployments.get(name)
                if existing is not None:
                    existing.callable_blob = d["callable_blob"]
                    existing.init_args_blob = d["init_args_blob"]
                    old_config = existing.config
                    existing.config = d["config"]
                    existing.route_prefix = d.get("route_prefix")
                    if not existing.config.autoscaling_config:
                        existing.target = d["config"].num_replicas
                    if (d["config"].user_config is not None
                            and d["config"].user_config
                            != old_config.user_config):
                        for h in existing.replicas.values():
                            # fire-and-forget reconfigure broadcast; the
                            # completed result is reclaimed after grace
                            h.reconfigure.remote(d["config"].user_config)  # graftlint: disable=GL015
                    existing.status = "UPDATING"
                else:
                    self._deployments[name] = _DeploymentState(
                        name, app_name, d["callable_blob"],
                        d["init_args_blob"], d["config"],
                        d.get("route_prefix"))
            # drop deployments of this app that were removed
            for name, st in list(self._deployments.items()):
                if st.app_name == app_name and name not in keep:
                    self._remove_deployment_locked(name)

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            for name, st in list(self._deployments.items()):
                if st.app_name == app_name:
                    self._remove_deployment_locked(name)

    def _remove_deployment_locked(self, name: str) -> None:
        # caller holds self._lock (the _locked suffix is the contract)
        st = self._deployments.pop(name)  # graftlint: disable=GL001
        for rid, h in st.replicas.items():
            events.emit("REPLICA_STOPPED",
                        message=f"{name}/{rid} deployment removed")
            try:
                ray_tpu.kill(h)
            except Exception:
                logger.exception("kill failed for a replica of %r "
                                 "during deployment removal", name)
        self._bump_locked()

    def get_replicas(self, deployment_name: str) -> tuple:
        """(version, [(replica_id, handle), ...]) for routers."""
        with self._lock:
            st = self._deployments.get(deployment_name)
            if st is None:
                return self._version, []
            return self._version, list(st.replicas.items())

    def poll_replicas(self, deployment_name: str, known_version: int,
                      timeout_s: float = 2.0) -> tuple:
        """Long-poll: return when the replica set changes past
        known_version or timeout (reference: long_poll.py:228)."""
        deadline = time.monotonic() + timeout_s
        with self._version_cv:
            while (self._version <= known_version
                   and not self._stop_event.is_set()
                   and time.monotonic() < deadline):
                self._version_cv.wait(timeout=max(
                    0.0, deadline - time.monotonic()))
        return self.get_replicas(deployment_name)

    def get_status(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "app": st.app_name,
                    "status": st.status,
                    "target_replicas": st.target,
                    "running_replicas": len(st.replicas),
                    "route_prefix": st.route_prefix,
                }
                for name, st in self._deployments.items()
            }

    def get_router_policy(self, deployment_name: str) -> str:
        """Routing policy for driver-side router construction
        ("pow2" | "prefix_aware")."""
        with self._lock:
            st = self._deployments.get(deployment_name)
            return (st.config.request_router if st is not None
                    else "pow2")

    def get_admission_config(self, deployment_name: str) -> dict:
        """Admission-control knobs for the driver-side
        AdmissionController (fetched on router refresh, so capacity
        tracks the live replica count)."""
        with self._lock:
            st = self._deployments.get(deployment_name)
            if st is None:
                return {"max_queued_requests": -1,
                        "max_ongoing_requests": 100,
                        "shed_queue_wait_s": 0.0,
                        "num_replicas": 0}
            return {
                "max_queued_requests": st.config.max_queued_requests,
                "max_ongoing_requests": st.config.max_ongoing_requests,
                "shed_queue_wait_s": st.config.shed_queue_wait_s,
                "num_replicas": len(st.replicas),
            }

    def report_slo_stats(self, deployment_name: str,
                         stats: Dict[str, float]) -> None:
        """Routers push their admission snapshot (queue depth, windowed
        p99, EWMA queue wait) here; the SLO autoscaling policy consumes
        it on the next reconcile tick. The registry metrics these come
        from live in the DRIVER process — the controller actor cannot
        read them, so the router pushes."""
        with self._lock:
            st = self._deployments.get(deployment_name)
            if st is not None:
                st.slo_stats = (time.monotonic(), dict(stats))

    def get_request_totals(self) -> Dict[str, float]:
        """deployment -> lifetime request count summed over replicas
        (feeds per-deployment QPS charts; reference:
        dashboard/modules/metrics serve panels).

        All replica probes are submitted up front and bounded by ONE
        wait (no serial per-replica timeouts on the scrape path). A
        deployment whose replicas ALL failed to answer is omitted —
        publishing 0 for a nonzero lifetime counter would make the
        series non-monotonic and chart a phantom QPS spike when it
        recovers."""
        import ray_tpu
        with self._lock:
            handles = {name: list(st.replicas.values())
                       for name, st in self._deployments.items()}
        probes = [(name, h.get_metrics.remote(2.0))
                  for name, replicas in handles.items()
                  for h in replicas]
        if not probes:
            return {name: 0.0 for name in handles}
        ready, _ = ray_tpu.wait([ref for _, ref in probes],
                                num_returns=len(probes), timeout=5)
        ready_set = set(r.id for r in ready)
        out: Dict[str, float] = {}
        answered: Dict[str, int] = {}
        for name, ref in probes:
            if ref.id not in ready_set:
                continue
            try:
                total = float(ray_tpu.get(ref, timeout=1)["total"])
            except Exception:  # noqa: BLE001 — replica died mid-probe
                continue
            out[name] = out.get(name, 0.0) + total
            answered[name] = answered.get(name, 0) + 1
        for name, replicas in handles.items():
            if not replicas:
                out.setdefault(name, 0.0)  # zero replicas: honest zero
            elif not answered.get(name):
                out.pop(name, None)  # nobody answered: omit, not 0
        return out

    def list_routes(self) -> Dict[str, str]:
        """route_prefix -> ingress deployment name (for the proxy)."""
        with self._lock:
            return {st.route_prefix: name
                    for name, st in self._deployments.items()
                    if st.route_prefix}

    def shutdown(self) -> None:
        with self._lock:
            for name in list(self._deployments):
                self._remove_deployment_locked(name)
            self._stop_event.set()
            self._version_cv.notify_all()

    def ping(self) -> str:
        return "pong"

    # -- reconcile --

    def _bump_locked(self) -> None:
        # caller holds self._lock (the _locked suffix is the contract)
        self._version += 1  # graftlint: disable=GL001
        self._version_cv.notify_all()

    def _reconcile_loop(self) -> None:
        # Event.wait instead of time.sleep: shutdown() wakes the loop
        # immediately instead of waiting out the reconcile interval
        while not self._stop_event.is_set():
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("reconcile pass failed")
            self._stop_event.wait(self._interval)

    def _reconcile_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._autoscale(st)
            self._health_check(st)
            self._scale_to_target(st)

    def _autoscale(self, st: _DeploymentState) -> None:
        from ray_tpu.autoscaler.policy import ReplicaMetrics, make_policy
        cfg: Optional[AutoscalingConfig] = st.config.autoscaling_config
        if cfg is None or not st.replicas:
            return
        policy_name = getattr(cfg, "policy", "ongoing") or "ongoing"
        if st.policy is None or st.policy_name != policy_name:
            st.policy = make_policy(policy_name)
            st.policy_name = policy_name
        metrics = ReplicaMetrics(running_replicas=len(st.replicas))
        if not st.policy.owns_hysteresis:
            # replica probes feed the target-ongoing-requests policy;
            # the SLO policy runs off router-pushed stats alone and
            # skips this per-tick probe fan-out
            totals = []
            for rid, h in list(st.replicas.items()):
                try:
                    m = ray_tpu.get(
                        h.get_metrics.remote(cfg.look_back_period_s),
                        timeout=1.0)
                    totals.append(m["avg_ongoing"])
                except Exception:  # graftlint: disable=GL004
                    pass  # replica unreachable: health check owns that
            if not totals:
                return
            metrics.total_ongoing = sum(totals)
        now = time.monotonic()
        with self._lock:
            if st.slo_stats is not None:
                t_recv, stats = st.slo_stats
                metrics.stats_age_s = now - t_recv
                metrics.queue_depth = float(
                    stats.get("queue_depth", 0.0))
                metrics.p99_latency_s = float(
                    stats.get("p99_latency_s", 0.0))
                metrics.ewma_queue_wait_s = float(
                    stats.get("ewma_queue_wait_s", 0.0))
        desired = st.policy.desired_replicas(metrics, cfg, st.target, now)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        with self._lock:
            if st.policy.owns_hysteresis:
                # the policy already damped flapping (sustained-breach /
                # sustained-calm windows); adopt its verdict directly
                if desired > st.target:
                    st.last_scale_up = now
                elif desired < st.target:
                    st.last_scale_down = now
                st.target = desired
            elif desired > st.target:
                if now - st.last_scale_up >= cfg.upscale_delay_s:
                    st.target = desired
                    st.last_scale_up = now
            elif desired < st.target:
                if now - st.last_scale_down >= cfg.downscale_delay_s:
                    st.target = desired
                    st.last_scale_down = now

    def _health_check(self, st: _DeploymentState) -> None:
        dead = []
        for rid, h in list(st.replicas.items()):
            try:
                ray_tpu.get(h.check_health.remote(), timeout=5.0)
            except Exception:
                dead.append(rid)
        if dead:
            with self._lock:
                for rid in dead:
                    h = st.replicas.pop(rid, None)
                    if h is not None:
                        events.emit("REPLICA_STOPPED", "WARNING",
                                    message=f"{st.name}/{rid} failed "
                                    "health check")
                        try:
                            ray_tpu.kill(h)
                        except Exception:
                            logger.exception(
                                "kill failed for dead replica %s", rid)
                self._bump_locked()

    def _scale_to_target(self, st: _DeploymentState) -> None:
        from ray_tpu.serve.replica import Replica
        with self._lock:
            delta = st.target - len(st.replicas)
        if delta > 0:
            ReplicaActor = ray_tpu.remote(Replica)
            new = {}
            for _ in range(delta):
                with self._lock:
                    rid = f"{st.name}#{st.next_replica_no}"
                    st.next_replica_no += 1
                opts = dict(st.config.ray_actor_options)
                opts.setdefault("max_concurrency",
                                max(4, min(st.config.max_ongoing_requests,
                                           32)))
                handle = ReplicaActor.options(**opts).remote(
                    st.name, rid, st.callable_blob, st.init_args_blob,
                    st.config.max_ongoing_requests,
                    st.config.user_config)
                new[rid] = handle
            # wait for constructors so routers never see half-born replicas
            for rid, h in list(new.items()):  # failures pop from `new`
                try:
                    ray_tpu.get(h.check_health.remote(), timeout=60.0)
                    events.emit("REPLICA_STARTED",
                                message=f"{st.name}/{rid}")
                except Exception:
                    logger.exception(
                        "replica %s failed construction health check; "
                        "discarding it", rid)
                    try:
                        ray_tpu.kill(h)
                    except Exception:  # graftlint: disable=GL004
                        pass  # best-effort: it never became healthy
                    new.pop(rid, None)
            with self._lock:
                st.replicas.update(new)
                st.status = ("HEALTHY" if len(st.replicas) >= st.target
                             else "UPDATING")
                self._bump_locked()
        elif delta < 0:
            with self._lock:
                victims = list(st.replicas)[delta:]
                doomed = [st.replicas.pop(rid) for rid in victims]
                st.status = "HEALTHY"
                self._bump_locked()
            for rid in victims:
                events.emit("REPLICA_STOPPED",
                            message=f"{st.name}/{rid} downscaled")
            for h in doomed:
                try:
                    # fire-and-forget pre-kill drain nudge; the replica
                    # dies right after, so nobody can hold the result
                    h.prepare_for_shutdown.remote()  # graftlint: disable=GL015
                    ray_tpu.kill(h)
                except Exception:
                    logger.exception("downscale shutdown failed for a "
                                     "replica of %r", st.name)
        else:
            with self._lock:
                if st.status != "HEALTHY" and len(st.replicas) >= st.target:
                    st.status = "HEALTHY"
