"""Serve local testing mode: in-process deployments, no cluster.

Reference: python/ray/serve/_private/local_testing_mode.py:49 —
``serve.run(app, local_testing_mode=True)`` instantiates every
deployment in the current process and returns a handle with
``DeploymentHandle`` semantics (``.remote()``/``.result()``,
method-attribute handles, ``options(stream=True)`` generators),
so deployment logic unit-tests run without ``ray_tpu.init``.

Divergences (stated): one in-process "replica" per deployment —
num_replicas / autoscaling / routing policies do not apply; calls run
on a fresh thread each (so composed deployments can call each other
without deadlock) with no max_ongoing_requests admission control.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.serve.deployment import Application


class LocalResponse:
    """Future-like result of a local handle call (mirrors
    DeploymentResponse.result)."""

    def __init__(self, fn, args, kwargs):
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def _run():
            try:
                self._value = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — re-raised in result
                self._error = e
            finally:
                self._done.set()

        threading.Thread(target=_run, daemon=True).start()

    def result(self, timeout_s: Optional[float] = None) -> Any:
        if not self._done.wait(timeout_s):
            raise TimeoutError(
                f"local deployment call not done after {timeout_s}s")
        if self._error is not None:
            raise self._error
        return self._value


class _LocalStream:
    """Iterable over a streaming local call (mirrors
    DeploymentResponseGenerator): a generator's items, or the single
    value of a non-generator handler."""

    def __init__(self, fn, args, kwargs):
        self._fn, self._args, self._kwargs = fn, args, kwargs

    def __iter__(self):
        out = self._fn(*self._args, **self._kwargs)
        if hasattr(out, "__iter__") and not isinstance(
                out, (str, bytes, dict)):
            yield from out
        else:
            yield out


class LocalDeploymentHandle:
    """DeploymentHandle look-alike bound to an in-process instance."""

    def __init__(self, instance: Any, method_name: str = "__call__",
                 stream: bool = False):
        self._instance = instance
        self._method_name = method_name
        self._stream = stream

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                **_ignored) -> "LocalDeploymentHandle":
        return LocalDeploymentHandle(
            self._instance, method_name or self._method_name,
            self._stream if stream is None else stream)

    def remote(self, *args, **kwargs):
        inst = self._instance
        import functools
        if isinstance(inst, functools.partial) or \
                (callable(inst) and not hasattr(inst, self._method_name)):
            if self._method_name != "__call__":
                raise AttributeError(
                    f"function deployment has no method "
                    f"{self._method_name!r}")
            fn = inst  # function deployment
        else:
            fn = getattr(inst, self._method_name)
        if self._stream:
            return _LocalStream(fn, args, kwargs)
        return LocalResponse(fn, args, kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return LocalDeploymentHandle(self._instance, name, self._stream)


def run_local(app: Application) -> LocalDeploymentHandle:
    """Instantiate the bound deployment graph in-process; nested
    Applications resolve to LocalDeploymentHandles (shared nodes
    instantiate once, matching deploy-time semantics)."""
    built: Dict[int, Any] = {}

    def build(node: Application):
        if id(node) in built:
            return built[id(node)]

        def resolve(v):
            return build(v) if isinstance(v, Application) else v

        args = tuple(resolve(a) for a in node.args)
        kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
        target = node.deployment.func_or_class
        if isinstance(target, type):
            instance = target(*args, **kwargs)
        elif args or kwargs:
            import functools
            instance = functools.partial(target, *args, **kwargs)
        else:
            instance = target
        handle = LocalDeploymentHandle(instance)
        built[id(node)] = handle
        return handle

    return build(app)
