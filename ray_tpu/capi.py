"""C API bridge: the language-neutral surface for non-Python clients.

Capability analog of the reference's C++ public API (reference:
cpp/include/ray/api.h — Put/Get/Task over the core worker). Divergence,
stated plainly: the reference runs C++ task *workers*; here C++ (or any
language) is a CLIENT of the cluster — it puts/gets raw byte objects
and invokes Python functions registered under names, executed as
ordinary tasks. The wire format is a dependency-free binary TLV over
the head's existing TCP listener (cpp/ holds the C++ client library).

Frames (little-endian, length-prefixed like every head connection):
  request  = [u32 len][u8 kind][body]
  reply    = [u32 len][u8 status(0 ok / 1 err)][body]
  kinds: 2 PUT   body = payload bytes          → ok body = 16B object id
         3 GET   body = 16B object id          → ok body = payload bytes
         4 CALL  body = u16 name_len, name, args bytes
                                               → ok body = result bytes
         5 DROP  body = 16B object id          → ok body = empty

A connection opens with the magic frame b"CAPI" + u32 version, which is
how the head tells a C client from a pickle-speaking peer (pickle
frames start with 0x80).

Python side::

    import ray_tpu
    from ray_tpu import capi
    ray_tpu.init(num_cpus=4, head_port=6379)
    capi.register_function("double", lambda b: b * 2)   # bytes -> bytes
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.protocol import recv_frame, send_frame

CAPI_MAGIC = b"CAPI"
CAPI_VERSION = 1
KV_NAMESPACE = "capi_functions"

_K_PUT, _K_GET, _K_CALL, _K_DROP = 2, 3, 4, 5
ID_LEN = 16  # ObjectID.binary() length
_OK, _ERR = 0, 1


def register_function(name: str, fn: Callable[[bytes], bytes]) -> None:
    """Expose ``fn`` (bytes -> bytes/str) to C-API clients under
    ``name``. Stored in the cluster KV so it survives the registering
    driver's module scope and is visible head-wide."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    blob = serialization.dumps(fn)
    if rt.is_driver:
        rt.gcs.kv.put(name.encode(), blob, namespace=KV_NAMESPACE)
    else:
        rt.gcs_call("kv_put", name.encode(), blob, KV_NAMESPACE)


class CapiSession:
    """Services one C-API connection on the head (its own thread —
    CALLs block on task results)."""

    def __init__(self, runtime, sock, first_frame: bytes):
        self.runtime = runtime
        self.sock = sock
        self._first = first_frame
        self._fn_cache: Dict[str, object] = {}
        self._held: set = set()
        self._lock = threading.Lock()

    def _reply(self, status: int, body: bytes = b"") -> None:
        send_frame(self.sock, bytes([status]) + body)

    def serve(self) -> None:
        try:
            if (len(self._first) < 8
                    or self._first[:4] != CAPI_MAGIC
                    or struct.unpack_from("<I", self._first, 4)[0]
                    != CAPI_VERSION):
                self._reply(_ERR, b"unsupported C-API version")
                return
            self._reply(_OK, b"")
            while True:
                frame = recv_frame(self.sock)
                if frame is None or not frame:
                    return
                try:
                    self._handle(frame[0], frame[1:])
                except Exception as exc:  # noqa: BLE001 — per-request
                    try:
                        self._reply(_ERR, repr(exc).encode())
                    except OSError:
                        return
        finally:
            self.close()

    def _handle(self, kind: int, body: bytes) -> None:
        rt = self.runtime
        if kind == _K_PUT:
            oid = ObjectID.from_random()
            # wrap as a serialized python `bytes` so Python tasks can
            # ray_tpu.get() C-created objects directly
            data, buffers = serialization.serialize(bytes(body))
            rt.store_packed_object(
                oid, serialization.pack_parts(data, buffers))
            with self._lock:
                self._held.add(oid)
            rt.reference_counter.add_local_reference(oid)
            self._reply(_OK, oid.binary())
        elif kind == _K_GET:
            oid = ObjectID(body[:ID_LEN])
            value = rt.get(ObjectRef(oid), timeout=60)
            if isinstance(value, str):
                value = value.encode()
            if not isinstance(value, (bytes, bytearray)):
                raise TypeError(
                    f"object {oid.hex()[:8]} is {type(value).__name__}, "
                    "not bytes — only byte objects cross the C API")
            self._reply(_OK, bytes(value))
        elif kind == _K_CALL:
            (name_len,) = struct.unpack_from("<H", body, 0)
            name = body[2:2 + name_len].decode()
            args = bytes(body[2 + name_len:])
            result = self._call(name, args)
            if isinstance(result, str):
                result = result.encode()
            if not isinstance(result, (bytes, bytearray)):
                raise TypeError(
                    f"registered function {name!r} returned "
                    f"{type(result).__name__}; must return bytes/str")
            self._reply(_OK, bytes(result))
        elif kind == _K_DROP:
            oid = ObjectID(body[:ID_LEN])
            with self._lock:
                if oid in self._held:
                    self._held.discard(oid)
                    self.runtime.reference_counter \
                        .remove_local_reference(oid)
            self._reply(_OK, b"")
        else:
            raise ValueError(f"unknown C-API request kind {kind}")

    def _call(self, name: str, args: bytes):
        # cache keyed by the registered blob, so re-registering a name
        # takes effect for connected sessions on their next call
        blob = self.runtime.gcs.kv.get(name.encode(),
                                       namespace=KV_NAMESPACE)
        if blob is None:
            raise KeyError(
                f"no C-API function registered under {name!r}")
        import hashlib
        digest = hashlib.sha1(blob).digest()
        cached = self._fn_cache.get(name)
        if cached is None or cached[0] != digest:
            from ray_tpu.core.remote_function import RemoteFunction
            cached = (digest, RemoteFunction(serialization.loads(blob)))
            self._fn_cache[name] = cached
        rf = cached[1]
        # runs as an ordinary task on the cluster — scheduling,
        # retries, and observability all apply
        from ray_tpu.core import runtime as runtime_mod
        prev = runtime_mod.get_runtime_or_none()
        if prev is None:
            runtime_mod.set_runtime(self.runtime)
        elif prev is not self.runtime:
            # the head re-initialized under this session: installing
            # our (dead) runtime as the global would clobber the new
            # driver — refuse instead
            raise RuntimeError(
                "cluster runtime changed since this C-API session "
                "connected; reconnect")
        ref = rf.remote(args)
        return self.runtime.get(ref, timeout=300)

    def close(self) -> None:
        with self._lock:
            held = list(self._held)
            self._held.clear()
        for oid in held:
            self.runtime.reference_counter.remove_local_reference(oid)
        try:
            self.sock.close()
        except OSError:
            pass
