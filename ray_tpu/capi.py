"""C API bridge: the language-neutral surface for non-Python clients.

Capability analog of the reference's C++ public API (reference:
cpp/include/ray/api.h — Put/Get/Task over the core worker). Divergence,
stated plainly: the reference runs C++ task *workers*; here C++ (or any
language) is a CLIENT of the cluster — it puts/gets raw byte objects
and invokes Python functions registered under names, executed as
ordinary tasks. The wire format is a dependency-free binary TLV over
the head's existing TCP listener (cpp/ holds the C++ client library).

Frames (little-endian, length-prefixed like every head connection):
  request  = [u32 len][u8 kind][body]
  reply    = [u32 len][u8 status(0 ok / 1 err)][body]
  kinds: 2 PUT   body = payload bytes          → ok body = 16B object id
         3 GET   body = 16B object id          → ok body = payload bytes
         4 CALL  body = u16 name_len, name, args bytes
                                               → ok body = result bytes
         5 DROP  body = 16B object id          → ok body = empty

C++ WORKER mode (reference: cpp/include/ray/api.h runs C++ tasks and
actors in C++ worker processes; here a worker process registers its
compiled functions/actor classes and the head pushes executions):
  6 WORKER_REGISTER body = u16 count, then per entry:
        u8 entry_kind (0 fn / 1 actor class), u16 name_len, name
    → ok reply, after which the connection is a worker channel:
  7 EXEC (head→worker, no reply frame — results arrive as kind 8):
        u64 call_id, u8 op (0 fn / 1 actor_new / 2 actor_call /
        3 actor_del), u64 instance_id, u16 name_len, name, args
  8 RESULT (worker→head):
        u64 call_id, u8 status, payload
        (actor_new payload = u64 instance id)

A connection opens with the magic frame b"CAPI" + u32 version, which is
how the head tells a C client from a pickle-speaking peer (pickle
frames start with 0x80).

Python side::

    import ray_tpu
    from ray_tpu import capi
    ray_tpu.init(num_cpus=4, head_port=6379)
    capi.register_function("double", lambda b: b * 2)   # bytes -> bytes
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.protocol import recv_frame, send_frame

CAPI_MAGIC = b"CAPI"
CAPI_VERSION = 1
KV_NAMESPACE = "capi_functions"

_K_PUT, _K_GET, _K_CALL, _K_DROP = 2, 3, 4, 5
_K_WORKER_REGISTER, _K_EXEC, _K_RESULT = 6, 7, 8
_OP_FN, _OP_ACTOR_NEW, _OP_ACTOR_CALL, _OP_ACTOR_DEL = 0, 1, 2, 3
ID_LEN = 16  # ObjectID.binary() length
_OK, _ERR = 0, 1
_EXEC_HEAD = struct.Struct("<QBQH")  # call_id, op, instance_id, name_len


class CppWorkerError(RuntimeError):
    """A C++ worker failed an execution (or died with calls in flight)."""


class _CppWorker:
    """Head-side record of one registered C++ worker connection."""

    def __init__(self, session, functions, actor_classes):
        self.session = session
        self.functions = set(functions)
        self.actor_classes = set(actor_classes)
        self.pending: Dict[int, ObjectID] = {}  # call_id -> result oid
        self.lock = threading.Lock()
        self.alive = True

    def send_exec(self, call_id: int, op: int, instance_id: int,
                  name: str, args: bytes, result_oid: ObjectID) -> None:
        encoded = name.encode()
        with self.lock:
            if not self.alive:
                raise CppWorkerError("C++ worker connection is closed")
            self.pending[call_id] = result_oid
        frame = (bytes([_K_EXEC])
                 + _EXEC_HEAD.pack(call_id, op, instance_id, len(encoded))
                 + encoded + args)
        self.session.send_locked(frame)


class CppWorkerManager:
    """Routes C++ task/actor executions to registered C++ workers
    (reference: worker-side cpp/include/ray/api.h — normal tasks pick
    any worker advertising the function; actor instances pin to the
    worker that created them)."""

    def __init__(self, runtime):
        self.runtime = runtime
        self._workers: list = []
        self._lock = threading.Lock()
        self._call_seq = 0
        self._rr = 0

    # -- registry --------------------------------------------------------
    def add_worker(self, worker: _CppWorker) -> None:
        with self._lock:
            self._workers.append(worker)

    def remove_worker(self, worker: _CppWorker) -> None:
        with self._lock:
            if worker in self._workers:
                self._workers.remove(worker)
        with worker.lock:
            worker.alive = False
            pending = dict(worker.pending)
            worker.pending.clear()
        err = CppWorkerError("C++ worker died with calls in flight")
        for oid in pending.values():
            self.runtime.task_manager.put_error(oid, err)

    def _pick(self, *, function: Optional[str] = None,
              actor_class: Optional[str] = None) -> _CppWorker:
        with self._lock:
            candidates = [w for w in self._workers
                          if (function in w.functions if function
                              else actor_class in w.actor_classes)]
            if not candidates:
                what = function or actor_class
                raise CppWorkerError(
                    f"no connected C++ worker provides {what!r}")
            self._rr += 1
            return candidates[self._rr % len(candidates)]

    def _next_call(self) -> int:
        with self._lock:
            self._call_seq += 1
            return self._call_seq

    # -- submissions -----------------------------------------------------
    def submit_task(self, name: str, args: bytes) -> ObjectRef:
        worker = self._pick(function=name)
        return self._submit(worker, _OP_FN, 0, name, args)

    def create_actor(self, class_name: str,
                     args: bytes = b"") -> "CppActorHandle":
        worker = self._pick(actor_class=class_name)
        ref = self._submit(worker, _OP_ACTOR_NEW, 0, class_name, args)
        raw = self.runtime.get(ref, timeout=60)
        (instance_id,) = struct.unpack("<Q", raw)
        return CppActorHandle(self, worker, class_name, instance_id)

    def _submit(self, worker: _CppWorker, op: int, instance_id: int,
                name: str, args: bytes) -> ObjectRef:
        call_id = self._next_call()
        oid = ObjectID.from_random()
        # ObjectRef's constructor registers the local reference; the
        # returned handle is the only pin, so results free when the
        # caller drops it.
        ref = ObjectRef(oid)
        worker.send_exec(call_id, op, instance_id, name, args, oid)
        return ref

    # -- results (called from the worker session's reader thread) -------
    def on_result(self, worker: _CppWorker, body: bytes) -> None:
        call_id, status = struct.unpack_from("<QB", body, 0)
        payload = bytes(body[9:])
        with worker.lock:
            oid = worker.pending.pop(call_id, None)
        if oid is None:
            return  # cancelled/duplicate
        rt = self.runtime
        if status != _OK:
            rt.task_manager.put_error(
                oid, CppWorkerError(payload.decode(errors="replace")))
            return
        data, buffers = serialization.serialize(payload)
        rt.store_packed_object(oid,
                               serialization.pack_parts(data, buffers))


class CppActorHandle:
    """Handle to a C++ actor instance, pinned to its worker
    (reference: ray::Actor(...).Remote() handles in cpp/ api.h)."""

    def __init__(self, manager: CppWorkerManager, worker: _CppWorker,
                 class_name: str, instance_id: int):
        self._manager = manager
        self._worker = worker
        self.class_name = class_name
        self.instance_id = instance_id

    def call(self, method: str, args: bytes = b"") -> ObjectRef:
        return self._manager._submit(
            self._worker, _OP_ACTOR_CALL, self.instance_id, method, args)

    def kill(self) -> None:
        try:
            self._manager._submit(
                self._worker, _OP_ACTOR_DEL, self.instance_id, "", b"")
        except CppWorkerError:
            pass  # worker already gone


def get_cpp_worker_manager(runtime=None) -> CppWorkerManager:
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime or runtime_mod.get_runtime()
    manager = getattr(rt, "_cpp_worker_manager", None)
    if manager is None:
        manager = rt._cpp_worker_manager = CppWorkerManager(rt)
    return manager


def cpp_task(name: str, args: bytes = b"") -> ObjectRef:
    """Run a function registered by a connected C++ worker; resolve the
    result with ray_tpu.get (bytes)."""
    return get_cpp_worker_manager().submit_task(name, bytes(args))


def cpp_actor(class_name: str, args: bytes = b"") -> CppActorHandle:
    """Instantiate a C++ actor class on a connected C++ worker."""
    return get_cpp_worker_manager().create_actor(class_name, bytes(args))


def register_function(name: str, fn: Callable[[bytes], bytes]) -> None:
    """Expose ``fn`` (bytes -> bytes/str) to C-API clients under
    ``name``. Stored in the cluster KV so it survives the registering
    driver's module scope and is visible head-wide."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    blob = serialization.dumps(fn)
    if rt.is_driver:
        rt.gcs.kv.put(name.encode(), blob, namespace=KV_NAMESPACE)
    else:
        rt.gcs_call("kv_put", name.encode(), blob, KV_NAMESPACE)


class CapiSession:
    """Services one C-API connection on the head (its own thread —
    CALLs block on task results)."""

    def __init__(self, runtime, sock, first_frame: bytes):
        self.runtime = runtime
        self.sock = sock
        self._first = first_frame
        self._fn_cache: Dict[str, object] = {}
        self._held: set = set()
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._worker: Optional[_CppWorker] = None

    def _reply(self, status: int, body: bytes = b"") -> None:
        with self._send_lock:
            send_frame(self.sock, bytes([status]) + body)

    def send_locked(self, frame: bytes) -> None:
        """Push a frame (EXEC) from any thread; serialized against
        replies on this connection."""
        with self._send_lock:
            send_frame(self.sock, frame)

    def serve(self) -> None:
        try:
            if (len(self._first) < 8
                    or self._first[:4] != CAPI_MAGIC
                    or struct.unpack_from("<I", self._first, 4)[0]
                    != CAPI_VERSION):
                self._reply(_ERR, b"unsupported C-API version")
                return
            from ray_tpu.core.config import (auth_token_matches,
                                             get_config)
            if get_config().auth_token:
                # token rides after the magic+version (absent = empty);
                # compared as raw bytes — this frame is never unpickled
                if not auth_token_matches(self._first[8:]):
                    self._reply(_ERR, b"authentication failed")
                    return
            self._reply(_OK, b"")
            while True:
                frame = recv_frame(self.sock)
                if frame is None or not frame:
                    return
                try:
                    self._handle(frame[0], frame[1:])
                except Exception as exc:  # noqa: BLE001 — per-request
                    try:
                        self._reply(_ERR, repr(exc).encode())
                    except OSError:
                        return
        finally:
            self.close()

    def _handle(self, kind: int, body: bytes) -> None:
        rt = self.runtime
        if kind == _K_PUT:
            oid = ObjectID.from_random()
            # wrap as a serialized python `bytes` so Python tasks can
            # ray_tpu.get() C-created objects directly
            data, buffers = serialization.serialize(bytes(body))
            rt.store_packed_object(
                oid, serialization.pack_parts(data, buffers))
            with self._lock:
                self._held.add(oid)
            rt.reference_counter.add_local_reference(oid)
            self._reply(_OK, oid.binary())
        elif kind == _K_GET:
            oid = ObjectID(body[:ID_LEN])
            value = rt.get(ObjectRef(oid), timeout=60)
            if isinstance(value, str):
                value = value.encode()
            if not isinstance(value, (bytes, bytearray)):
                raise TypeError(
                    f"object {oid.hex()[:8]} is {type(value).__name__}, "
                    "not bytes — only byte objects cross the C API")
            self._reply(_OK, bytes(value))
        elif kind == _K_CALL:
            (name_len,) = struct.unpack_from("<H", body, 0)
            name = body[2:2 + name_len].decode()
            args = bytes(body[2 + name_len:])
            result = self._call(name, args)
            if isinstance(result, str):
                result = result.encode()
            if not isinstance(result, (bytes, bytearray)):
                raise TypeError(
                    f"registered function {name!r} returned "
                    f"{type(result).__name__}; must return bytes/str")
            self._reply(_OK, bytes(result))
        elif kind == _K_DROP:
            oid = ObjectID(body[:ID_LEN])
            with self._lock:
                if oid in self._held:
                    self._held.discard(oid)
                    self.runtime.reference_counter \
                        .remove_local_reference(oid)
            self._reply(_OK, b"")
        elif kind == _K_WORKER_REGISTER:
            (count,) = struct.unpack_from("<H", body, 0)
            offset = 2
            functions, actor_classes = [], []
            for _ in range(count):
                entry_kind = body[offset]
                (name_len,) = struct.unpack_from("<H", body, offset + 1)
                offset += 3
                name = body[offset:offset + name_len].decode()
                offset += name_len
                (actor_classes if entry_kind == 1
                 else functions).append(name)
            self._worker = _CppWorker(self, functions, actor_classes)
            # Ack BEFORE publishing to the manager: once the worker is
            # visible, another thread may push an EXEC frame, and the
            # worker's constructor must not read that frame as its
            # registration ack.
            self._reply(_OK, b"")
            get_cpp_worker_manager(self.runtime).add_worker(self._worker)
        elif kind == _K_RESULT:
            if self._worker is None:
                raise ValueError("RESULT frame before WORKER_REGISTER")
            # no reply: results flow head-ward only
            get_cpp_worker_manager(self.runtime).on_result(
                self._worker, body)
        else:
            raise ValueError(f"unknown C-API request kind {kind}")

    def _call(self, name: str, args: bytes):
        # cache keyed by the registered blob, so re-registering a name
        # takes effect for connected sessions on their next call
        blob = self.runtime.gcs.kv.get(name.encode(),
                                       namespace=KV_NAMESPACE)
        if blob is None:
            raise KeyError(
                f"no C-API function registered under {name!r}")
        import hashlib
        digest = hashlib.sha1(blob).digest()
        cached = self._fn_cache.get(name)
        if cached is None or cached[0] != digest:
            from ray_tpu.core.remote_function import RemoteFunction
            cached = (digest, RemoteFunction(serialization.loads(blob)))
            # digest-keyed last-write-wins cache: concurrent writers
            # store equivalent values, so lock-free is benign
            self._fn_cache[name] = cached  # graftlint: disable=GL001
        rf = cached[1]
        # runs as an ordinary task on the cluster — scheduling,
        # retries, and observability all apply
        from ray_tpu.core import runtime as runtime_mod
        prev = runtime_mod.get_runtime_or_none()
        if prev is None:
            runtime_mod.set_runtime(self.runtime)
        elif prev is not self.runtime:
            # the head re-initialized under this session: installing
            # our (dead) runtime as the global would clobber the new
            # driver — refuse instead
            raise RuntimeError(
                "cluster runtime changed since this C-API session "
                "connected; reconnect")
        ref = rf.remote(args)
        return self.runtime.get(ref, timeout=300)

    def close(self) -> None:
        if self._worker is not None:
            get_cpp_worker_manager(self.runtime).remove_worker(
                self._worker)
            self._worker = None
        with self._lock:
            held = list(self._held)
            self._held.clear()
        for oid in held:
            self.runtime.reference_counter.remove_local_reference(oid)
        try:
            self.sock.close()
        except OSError:
            pass
