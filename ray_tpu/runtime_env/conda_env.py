"""conda runtime envs: named or created-on-demand conda environments
the worker re-execs into.

Capability parity with the reference's conda plugin
(reference: python/ray/_private/runtime_env/conda.py:297 — named envs
resolve to an existing prefix; dict specs create a content-hashed env
under the cache dir). Same flock + ready-marker discipline as
pip_env.py; the worker re-exec mechanism is shared (core/worker.main).

The conda executable resolves from ``RTPU_CONDA_EXE`` (tests inject a
fake here) or PATH (conda/mamba/micromamba).
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import shutil
import subprocess
from typing import Dict, Union

from ray_tpu.runtime_env.packaging import cache_root


def conda_exe() -> str:
    exe = os.environ.get("RTPU_CONDA_EXE")
    if exe:
        return exe
    for name in ("conda", "mamba", "micromamba"):
        found = shutil.which(name)
        if found:
            return found
    raise RuntimeError(
        "runtime_env['conda'] requires a conda executable on this node "
        "(conda/mamba/micromamba on PATH, or RTPU_CONDA_EXE)")


def conda_env_hash(spec: Dict) -> str:
    return hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def _named_env_python(exe: str, name: str) -> str:
    """Resolve an EXISTING named env to its interpreter via
    `conda env list --json` (reference: conda.py get_conda_env_list)."""
    proc = subprocess.run([exe, "env", "list", "--json"],
                          capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        raise RuntimeError(
            f"conda env list failed: {proc.stderr.strip()[-500:]}")
    prefixes = json.loads(proc.stdout).get("envs", [])
    for prefix in prefixes:
        if os.path.basename(prefix) == name or prefix == name:
            python = os.path.join(prefix, "bin", "python")
            if os.path.exists(python):
                return python
    if name == "base":
        # The base env is the install root, whose basename is e.g.
        # "miniconda3", never "base": it's the prefix NOT under envs/.
        for prefix in prefixes:
            if os.path.basename(os.path.dirname(prefix)) != "envs":
                python = os.path.join(prefix, "bin", "python")
                if os.path.exists(python):
                    return python
    raise RuntimeError(
        f"conda env {name!r} not found (known: "
        f"{[os.path.basename(p) for p in prefixes]})")


def ensure_conda_env(conda_spec: Union[str, Dict]) -> str:
    """Resolve (named) or create (dict spec) the conda env; returns the
    path to its python interpreter."""
    exe = conda_exe()
    if isinstance(conda_spec, str):
        return _named_env_python(exe, conda_spec)

    digest = conda_env_hash(conda_spec)
    root = cache_root()
    env_dir = os.path.join(root, f"conda-{digest}")
    python = os.path.join(env_dir, "bin", "python")
    marker = os.path.join(env_dir, ".rtpu_ready")
    if os.path.exists(marker):
        os.utime(env_dir)
        return python
    lock_path = os.path.join(root, f".conda-{digest}.lock")
    os.makedirs(root, exist_ok=True)
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        if os.path.exists(marker):  # built while we waited
            return python
        if os.path.exists(env_dir):
            shutil.rmtree(env_dir)  # half-built leftover
        yml_path = os.path.join(root, f".conda-{digest}.yml")
        with open(yml_path, "w") as f:
            json.dump(conda_spec, f)  # YAML is a JSON superset
        proc = subprocess.run(
            [exe, "env", "create", "-p", env_dir, "-f", yml_path,
             "--yes"],
            capture_output=True, text=True)
        if proc.returncode != 0 or not os.path.exists(python):
            tail = (proc.stdout + proc.stderr)[-800:]
            shutil.rmtree(env_dir, ignore_errors=True)
            raise RuntimeError(f"conda env create failed: {tail}")
        with open(marker, "w") as f:
            f.write("ok")
    return python
