"""Container runtime envs: workers launched inside an image.

Capability parity with the reference's image_uri/container plugin
(reference: python/ray/_private/runtime_env/image_uri.py:24 — worker
processes run under podman with the session/cache dirs mounted; on GKE
TPU fleets this is how runtimes are pinned). The node wraps the worker
argv in a container-runtime invocation; everything else (socket, shm
store, env vars) passes through via host networking + mounts.

The runtime binary resolves from ``RTPU_CONTAINER_RUNTIME`` (tests
inject a fake here) or PATH (podman preferred, docker fallback).
"""

from __future__ import annotations

import os
import shutil
from typing import Dict, List, Optional


def container_runtime_exe() -> str:
    exe = os.environ.get("RTPU_CONTAINER_RUNTIME")
    if exe:
        return exe
    for name in ("podman", "docker"):
        found = shutil.which(name)
        if found:
            return found
    raise RuntimeError(
        "runtime_env['image_uri'] requires a container runtime on this "
        "node (podman/docker on PATH, or RTPU_CONTAINER_RUNTIME)")


def container_worker_command(image_uri: str, worker_cmd: List[str],
                             env: Dict[str, str], *,
                             mounts: Optional[List[str]] = None,
                             devices: Optional[List[str]] = None
                             ) -> List[str]:
    """Wrap a worker argv to run inside ``image_uri``.

    Host networking + IPC so the unix socket and shm arena work
    unchanged; the session/cache dirs and the framework source mount
    read-write/read-only respectively; TPU device nodes map via
    --device (host /dev is NOT visible through net/ipc sharing);
    RTPU_*/TPU_*/JAX_* env vars are forwarded explicitly (container
    runtimes don't inherit).
    """
    exe = container_runtime_exe()
    cmd = [exe, "run", "--rm", "--network=host", "--ipc=host"]
    for mount in mounts or ():
        cmd += ["-v", mount]
    for device in devices or ():
        cmd += ["--device", device]
    for key, value in sorted(env.items()):
        if key.startswith(("RTPU_", "TPU_", "JAX_", "PYTHON")):
            cmd += ["--env", f"{key}={value}"]
    cmd.append(image_uri)
    return cmd + list(worker_cmd)
