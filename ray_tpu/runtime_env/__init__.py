"""Runtime environments: per-task/actor execution environments.

Capability parity with the reference's runtime_env subsystem
(reference: python/ray/_private/runtime_env/ — plugins for
env_vars/working_dir/py_modules/pip with URI-cached packages staged by a
per-node agent; python/ray/_private/runtime_env/plugin.py plugin ABC,
packaging.py zip+hash upload, uri_cache.py).

Design (TPU-first, daemonless): there is no separate runtime-env agent
process. The *driver* packages local directories into content-addressed
archives in the GCS KV (`packaging.upload_package`); the *worker
process* applies its environment at startup, before its task loop —
fetching archives over its existing blocking GCS bridge, extracting into
a node-local content-addressed cache (flock-guarded, LRU-pruned), and
for `pip` envs re-exec()ing into a cached virtualenv before connecting.
Workers with different runtime envs never share a pool slot: the node's
worker pool is keyed by (hardware profile, runtime-env hash).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

# Fields a runtime env may carry. Anything else is rejected up front so
# typos fail at submit time, not silently at worker start.
_KNOWN_FIELDS = ("env_vars", "working_dir", "py_modules", "pip",
                 "conda", "image_uri", "excludes", "config")


class RuntimeEnv(dict):
    """A validated runtime environment description.

    reference: python/ray/runtime_env/runtime_env.py — the user-facing
    dict-like wrapper. Accepts:
      env_vars:    {str: str}
      working_dir: local directory path (packaged at submit) or kv:// URI
      py_modules:  list of local module-dir paths or kv:// URIs
      pip:         list of requirement strings, or {"packages": [...],
                   "pip_install_options": [...]}
      excludes:    fnmatch patterns skipped when packaging working_dir
    """

    def __init__(self, **kwargs: Any):
        super().__init__()
        for key, value in kwargs.items():
            if value is None:
                continue
            if key not in _KNOWN_FIELDS:
                raise ValueError(
                    f"unknown runtime_env field {key!r}; "
                    f"supported: {_KNOWN_FIELDS}")
            self[key] = value
        validate_runtime_env(self)


def validate_runtime_env(env: Dict[str, Any]) -> None:
    for key in env:
        if key not in _KNOWN_FIELDS:
            raise ValueError(
                f"unknown runtime_env field {key!r}; "
                f"supported: {_KNOWN_FIELDS}")
    env_vars = env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be {str: str}")
    working_dir = env.get("working_dir")
    if working_dir is not None and not isinstance(working_dir, str):
        raise TypeError("runtime_env['working_dir'] must be a path or URI")
    py_modules = env.get("py_modules")
    if py_modules is not None and not isinstance(py_modules, (list, tuple)):
        raise TypeError("runtime_env['py_modules'] must be a list")
    pip = env.get("pip")
    if pip is not None and not isinstance(pip, (list, tuple, dict)):
        raise TypeError("runtime_env['pip'] must be a list of requirements "
                        "or a dict with 'packages'")
    conda = env.get("conda")
    if conda is not None and not isinstance(conda, (str, dict)):
        raise TypeError("runtime_env['conda'] must be an env name or a "
                        "dict spec (environment.yml shape)")
    if pip is not None and conda is not None:
        # reference: conda.py — pip deps go INSIDE the conda spec
        raise ValueError("runtime_env cannot set both 'pip' and 'conda'; "
                         "put pip packages inside the conda spec")
    image_uri = env.get("image_uri")
    if image_uri is not None and not isinstance(image_uri, str):
        raise TypeError("runtime_env['image_uri'] must be a string")
    if image_uri is not None and (pip is not None or conda is not None):
        # A host-built venv/conda prefix is meaningless inside the
        # image (interpreter paths differ); bake packages into the
        # image instead (reference: image_uri.py precludes pip/conda).
        raise ValueError("runtime_env cannot combine 'image_uri' with "
                         "'pip'/'conda'; bake packages into the image")


def normalize_runtime_env(env: Optional[Dict[str, Any]],
                          runtime) -> Optional[Dict[str, Any]]:
    """Resolve local paths into content-addressed kv:// URIs and return
    a canonical, fully-portable env dict (or None if empty). The result
    is safe to ship inside a TaskSpec to any node."""
    if not env:
        return None
    validate_runtime_env(env)
    from ray_tpu.runtime_env import packaging
    out: Dict[str, Any] = {}
    env_vars = env.get("env_vars")
    if env_vars:
        out["env_vars"] = dict(sorted(env_vars.items()))
    excludes = list(env.get("excludes") or ())
    working_dir = env.get("working_dir")
    if working_dir:
        if working_dir.startswith("kv://"):
            out["working_dir"] = working_dir
        else:
            out["working_dir"] = packaging.upload_package(
                runtime, working_dir, excludes=excludes)
    py_modules = env.get("py_modules")
    if py_modules:
        uris = []
        for mod in py_modules:
            if isinstance(mod, str) and mod.startswith("kv://"):
                uris.append(mod)
            else:
                base = os.path.basename(
                    os.path.abspath(os.path.expanduser(mod)))
                wrap = "" if os.path.isfile(mod) else base
                uris.append(packaging.upload_package(
                    runtime, mod, excludes=excludes, wrap=wrap))
        out["py_modules"] = uris
    pip = env.get("pip")
    if pip:
        if isinstance(pip, dict):
            out["pip"] = {
                "packages": list(pip.get("packages") or ()),
                "pip_install_options": list(
                    pip.get("pip_install_options") or ()),
            }
        else:
            out["pip"] = {"packages": list(pip), "pip_install_options": []}
    conda = env.get("conda")
    if conda:
        # str = named env (resolved node-side); dict = canonicalized spec
        out["conda"] = (conda if isinstance(conda, str)
                        else json.loads(json.dumps(conda, sort_keys=True)))
    if env.get("image_uri"):
        out["image_uri"] = env["image_uri"]
    if env.get("config"):
        out["config"] = dict(env["config"])
    if not out:
        return None
    return out


def runtime_env_hash(env: Dict[str, Any]) -> str:
    """Stable content hash of a *normalized* env — the worker-pool key."""
    blob = json.dumps(env, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def merge_runtime_envs(parent: Optional[Dict[str, Any]],
                       child: Optional[Dict[str, Any]],
                       ) -> Optional[Dict[str, Any]]:
    """Child tasks inherit the parent's env; an explicit child env
    overrides per-field, with env_vars merged key-wise (reference
    semantics: runtime_env inheritance merges env_vars, replaces other
    fields)."""
    if not parent:
        return child
    if not child:
        return parent
    merged = dict(parent)
    for key, value in child.items():
        if key == "env_vars" and parent.get("env_vars"):
            combined = dict(parent["env_vars"])
            combined.update(value)
            merged["env_vars"] = combined
        else:
            merged[key] = value
    return merged


def current_runtime_env() -> Optional[Dict[str, Any]]:
    """The runtime env of the current worker process (None on the
    driver or for default-env workers)."""
    blob = os.environ.get("RTPU_RUNTIME_ENV")
    return json.loads(blob) if blob else None
