"""pip runtime envs: cached virtualenvs the worker re-execs into.

Capability parity with the reference's pip plugin
(reference: python/ray/_private/runtime_env/pip.py — a virtualenv per
unique requirement set, created with --system-site-packages so the
cluster's own packages stay importable, populated by pip, cached and
shared across workers).

The venv is keyed by the hash of the requirement list and built under
the same flock discipline as extracted packages. The worker process
checks for a pip env *before* connecting to its node and re-exec()s into
the venv's interpreter (reference: worker startup inside the activated
env), so user imports resolve against the installed packages with zero
per-task overhead.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import shutil
import subprocess
import sys
from typing import Dict

from ray_tpu.runtime_env.packaging import cache_root


def pip_env_hash(pip_spec: Dict) -> str:
    blob = json.dumps(pip_spec, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def ensure_pip_env(pip_spec: Dict) -> str:
    """Create (or reuse) the virtualenv for ``pip_spec``; returns the
    path to its python interpreter. Raises RuntimeError with pip's
    output on install failure so the scheduling error is actionable."""
    digest = pip_env_hash(pip_spec)
    root = cache_root()
    venv_dir = os.path.join(root, f"venv-{digest}")
    python = os.path.join(venv_dir, "bin", "python")
    marker = os.path.join(venv_dir, ".rtpu_ready")
    if os.path.exists(marker):
        os.utime(venv_dir)
        return python
    lock_path = os.path.join(root, f".venv-{digest}.lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        if os.path.exists(marker):
            os.utime(venv_dir)
            return python
        try:
            # --system-site-packages: jax/numpy/the framework itself come
            # from the host install; the venv only layers the requested
            # packages on top (reference: pip.py same flag).
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 venv_dir],
                check=True, capture_output=True)
            # --system-site-packages chains to the BASE interpreter; if
            # this process itself runs in a venv (common in container
            # images), that venv's packages would vanish. Chain the
            # parent's import paths explicitly via a .pth file.
            import glob as _glob
            site_dirs = _glob.glob(
                os.path.join(venv_dir, "lib", "python*", "site-packages"))
            if site_dirs:
                parent_paths = [p for p in sys.path
                                if p and os.path.isdir(p)]
                with open(os.path.join(site_dirs[0],
                                       "zzz_rtpu_parent.pth"), "w") as f:
                    f.write("\n".join(parent_paths) + "\n")
            packages = list(pip_spec.get("packages") or ())
            if packages:
                cmd = [python, "-m", "pip", "install",
                       "--disable-pip-version-check", "--no-input"]
                cmd += list(pip_spec.get("pip_install_options") or ())
                cmd += packages
                proc = subprocess.run(cmd, capture_output=True, text=True)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install failed for runtime_env "
                        f"{packages}:\n{proc.stdout}\n{proc.stderr}")
            with open(marker, "w") as f:
                f.write("ok")
        except BaseException:
            shutil.rmtree(venv_dir, ignore_errors=True)
            raise
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)
    return python
