"""Packaging: content-addressed archives for working_dir / py_modules.

Capability parity with the reference's package pipeline
(reference: python/ray/_private/runtime_env/packaging.py —
zip-with-excludes, content hash → gcs:// URI, upload once, per-node
download + extract into a URI cache; uri_cache.py LRU bounded by size).

Archives live in the GCS KV under the ``runtime_env`` namespace keyed by
content hash, so identical directories upload exactly once per cluster.
Extraction on each node goes into a content-addressed cache directory
guarded by an flock (many workers may start concurrently) and pruned
LRU when it exceeds ``runtime_env_cache_bytes``.
"""

from __future__ import annotations

import fcntl
import fnmatch
import hashlib
import io
import os
import shutil
import tempfile
import zipfile
from typing import List, Optional

KV_NAMESPACE = "runtime_env"
# Refuse to package directories larger than this (reference caps uploads
# at ~500MB; huge working dirs belong in real storage, not the KV).
MAX_PACKAGE_BYTES = 512 * 1024 * 1024
_ALWAYS_EXCLUDE = ("__pycache__", "*.pyc", ".git")


def _iter_files(root: str, excludes: List[str]):
    patterns = list(excludes) + list(_ALWAYS_EXCLUDE)

    def skip(rel: str) -> bool:
        parts = rel.split(os.sep)
        return any(
            fnmatch.fnmatch(part, pat) or fnmatch.fnmatch(rel, pat)
            for part in parts for pat in patterns)

    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        dirnames[:] = [
            d for d in dirnames
            if not skip(os.path.normpath(os.path.join(rel_dir, d)))]
        for name in sorted(filenames):
            rel = os.path.normpath(os.path.join(rel_dir, name))
            if not skip(rel):
                yield rel


def package_directory(path: str,
                      excludes: Optional[List[str]] = None,
                      wrap: str = "") -> bytes:
    """Zip ``path`` deterministically (sorted entries, fixed mtimes) so
    the archive bytes — and thus the URI — depend only on content.
    ``wrap`` prefixes every entry with a directory name — used for
    py_modules, where the extracted root must *contain* the package dir
    so it can go on sys.path (reference: packaging.py py_modules zips
    the module directory itself, working_dir zips its contents)."""
    path = os.path.abspath(os.path.expanduser(path))
    if os.path.isfile(path):
        # single-file module (py_modules accepts lone .py files)
        with open(path, "rb") as f:
            data = f.read()
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            info = zipfile.ZipInfo(os.path.basename(path),
                                   date_time=(2000, 1, 1, 0, 0, 0))
            zf.writestr(info, data)
        return buf.getvalue()
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    buf = io.BytesIO()
    total = 0
    prefix = f"{wrap}/" if wrap else ""
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel in sorted(_iter_files(path, list(excludes or ()))):
            full = os.path.join(path, rel)
            total += os.path.getsize(full)
            if total > MAX_PACKAGE_BYTES:
                raise ValueError(
                    f"runtime_env package {path} exceeds "
                    f"{MAX_PACKAGE_BYTES} bytes; use excludes or "
                    "external storage")
            info = zipfile.ZipInfo(prefix + rel,
                                   date_time=(2000, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as f:
                zf.writestr(info, f.read())
    return buf.getvalue()


def upload_package(runtime, path: str,
                   excludes: Optional[List[str]] = None,
                   wrap: str = "") -> str:
    """Package ``path`` and store it in the cluster KV; returns its
    ``kv://pkg/<sha1>/<basename>`` URI. Idempotent by content."""
    data = package_directory(path, excludes, wrap=wrap)
    digest = hashlib.sha1(data).hexdigest()
    base = os.path.basename(os.path.abspath(os.path.expanduser(path)))
    uri = f"kv://pkg/{digest}/{base}"
    key = f"pkg/{digest}".encode()
    if not runtime.gcs_call("kv_exists", key, KV_NAMESPACE):
        runtime.gcs_call("kv_put", key, data, KV_NAMESPACE)
    return uri


def parse_uri(uri: str):
    if not uri.startswith("kv://pkg/"):
        raise ValueError(f"unsupported runtime_env URI: {uri}")
    rest = uri[len("kv://"):]
    parts = rest.split("/")
    digest = parts[1]
    base = parts[2] if len(parts) > 2 else "pkg"
    return f"pkg/{digest}".encode(), digest, base


def cache_root() -> str:
    root = os.environ.get(
        "RTPU_RUNTIME_ENV_CACHE",
        os.path.join(tempfile.gettempdir(), "rtpu_runtime_resources"))
    os.makedirs(root, exist_ok=True)
    return root


def fetch_package(uri: str, kv_get) -> str:
    """Ensure the package behind ``uri`` is extracted into the node-local
    cache; returns the extracted directory. ``kv_get(key, namespace)``
    is any blocking KV fetch (driver-direct or the worker's GCS bridge).
    Concurrent workers coordinate through an flock; the extract is
    atomic (tempdir + rename) so a crash mid-extract never poisons the
    cache."""
    key, digest, _base = parse_uri(uri)
    root = cache_root()
    target = os.path.join(root, digest)
    if os.path.isdir(target):
        os.utime(target)  # LRU touch
        return target
    lock_path = os.path.join(root, f".{digest}.lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if os.path.isdir(target):
                os.utime(target)
                return target
            data = kv_get(key, KV_NAMESPACE)
            if data is None:
                raise RuntimeError(
                    f"runtime_env package {uri} not found in the cluster "
                    "KV (was the cluster restarted?)")
            tmp = tempfile.mkdtemp(prefix=f".{digest}.", dir=root)
            try:
                with zipfile.ZipFile(io.BytesIO(data)) as zf:
                    zf.extractall(tmp)
                    for info in zf.infolist():
                        mode = info.external_attr >> 16
                        if mode:
                            os.chmod(os.path.join(tmp, info.filename),
                                     mode & 0o777)
                os.rename(tmp, target)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)
    _prune_cache(root, keep=digest)
    return target


def _prune_cache(root: str, keep: str) -> None:
    """LRU-prune extracted packages beyond the size budget (reference:
    uri_cache.py). Entries are whole directories; in-use entries are
    protected only by recency — matching the reference's best-effort
    deletion of unused URIs."""
    from ray_tpu.core.config import get_config
    budget = getattr(get_config(), "runtime_env_cache_bytes",
                     10 * 1024 * 1024 * 1024)
    entries = []
    total = 0
    for name in os.listdir(root):
        full = os.path.join(root, name)
        if name.startswith(".") or not os.path.isdir(full):
            continue
        if name.startswith("venv-"):
            # Never prune virtualenvs: a long-lived worker is executing
            # *from* its venv (its mtime reflects spawn time, not use),
            # and deleting it under a running interpreter breaks every
            # later import. Venvs are bounded by distinct pip specs and
            # reclaimed only by explicit cache cleanup.
            continue
        size = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _dn, fn in os.walk(full) for f in fn)
        entries.append((os.stat(full).st_mtime, size, name, full))
        total += size
    entries.sort()
    for _mtime, size, name, full in entries:
        if total <= budget:
            break
        if name == keep:
            continue
        shutil.rmtree(full, ignore_errors=True)
        total -= size
