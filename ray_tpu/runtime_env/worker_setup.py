"""Worker-side runtime env application.

The reference stages runtime envs through a per-node agent process the
raylet consults before launching the worker (reference:
src/ray/raylet/runtime_env_agent_client.cc,
python/ray/_private/runtime_env/agent/). Here the worker process itself
applies its env at startup, before entering its task loop: it already
has a blocking GCS bridge through its node connection, so no extra
daemon or HTTP hop is needed — and a failed setup surfaces as a worker
startup failure on exactly the task that required the env.

Order of application:
  1. pip      — handled even earlier, pre-connect (see core/worker.main:
                re-exec into the cached venv's interpreter)
  2. env_vars — os.environ, before any user import runs
  3. working_dir — fetch+extract, chdir, sys.path[0]
  4. py_modules  — fetch+extract each, prepend to sys.path
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List

from ray_tpu.core import serialization


def _sync_gcs_call(conn, deferred: List[dict], method: str, *args) -> Any:
    """One blocking GCS call over the raw node connection, used before
    the worker's reply-routing loop exists. Non-reply messages that
    arrive meanwhile (e.g. an eager task dispatch) are deferred for the
    main loop — worker task execution is FIFO, so this preserves order."""
    conn.send({"kind": "GCS_REQUEST", "method": method,
               "args": serialization.dumps(args), "req_id": None})
    while True:
        msg = conn.recv()
        if msg is None:
            raise RuntimeError(
                "node connection closed during runtime_env setup")
        if msg.get("kind") == "GCS_REPLY":
            if msg.get("error"):
                raise serialization.loads(msg["error"])
            return serialization.loads(msg["result"])
        deferred.append(msg)


def apply_runtime_env(env_json: str, conn, deferred: List[dict]) -> None:
    """Apply this worker's runtime env (normalized JSON). Called from
    worker_main after REGISTER, before the message loop."""
    env: Dict[str, Any] = json.loads(env_json)
    env_vars = env.get("env_vars")
    if env_vars:
        os.environ.update(env_vars)
    from ray_tpu.runtime_env import packaging

    def kv_get(key, namespace):
        return _sync_gcs_call(conn, deferred, "kv_get", key, namespace)

    working_dir = env.get("working_dir")
    if working_dir:
        path = packaging.fetch_package(working_dir, kv_get)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    for uri in env.get("py_modules") or ():
        path = packaging.fetch_package(uri, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
