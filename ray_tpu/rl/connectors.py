"""Connectors: composable observation/reward transforms between env and
module.

Reference: rllib/connectors/ — ConnectorV2 pipelines transforming data
on the env→module path (frame stacking, observation normalization) and
the learner path, with state that syncs from env runners to the
learner. Here a connector is a small stateful object with two hooks:

  on_obs(obs [N, ...]) -> transformed obs     (every policy query)
  on_batch(SampleBatch) -> SampleBatch        (post-rollout, pre-learn)

Pipelines apply connectors in order; ``get_state``/``set_state`` let
an algorithm broadcast driver-merged statistics (e.g. running obs
mean/var) back to remote env-runner actors, the reference's
connector-state sync.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.sample_batch import FINAL_OBS, OBS, REWARDS, SampleBatch


class Connector:
    def on_obs(self, obs: np.ndarray,
               resets: Optional[np.ndarray] = None) -> np.ndarray:
        """``resets``: bool [N] marking envs whose obs is a fresh
        episode's first observation (stateful connectors must not leak
        the previous episode into it)."""
        return obs

    def merge_states(self, states: list) -> Dict[str, Any]:
        """Combine per-runner states into one (driver-side merge before
        broadcast; reference: connector-state aggregation). The inputs
        must cover DISJOINT samples — the sync protocol passes the
        driver's canonical state plus per-runner deltas
        (``pop_delta_state``), never two copies of shared history."""
        return states[0] if states else {}

    def pop_delta_state(self) -> Dict[str, Any]:
        """Return (and clear) the state accumulated since the last sync
        (reference: rllib filters' apply_changes delta buffers)."""
        return {}

    def on_batch(self, batch: SampleBatch) -> SampleBatch:
        return batch

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    def obs_dim_multiplier(self) -> int:
        """How this connector scales the flat obs dim (FrameStack > 1)."""
        return 1


class ObsNormalizer(Connector):
    """Running mean/var normalization (reference:
    rllib/connectors/env_to_module/mean_std_filter.py). Statistics
    update from every observed obs; normalized output is clipped."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None  # sum of squared deviations
        # snapshot at the last sync: pop_delta_state derives the
        # since-sync delta by inverse Chan merge, so the hot update
        # loop pays nothing for the sync protocol
        self._snap = (0.0, None, None)

    def _update(self, obs: np.ndarray) -> None:
        flat = obs.reshape(-1, obs.shape[-1]).astype(np.float64)
        if self.mean is None:
            self.mean = np.zeros(flat.shape[-1])
            # zeros, not ones: a ones-init biases the variance by
            # 1/(count-1); _apply's eps already guards the divide
            self.m2 = np.zeros(flat.shape[-1])
        for row in flat:  # Welford; rollout sizes keep this cheap
            self.count += 1.0
            delta = row - self.mean
            self.mean += delta / self.count
            self.m2 += delta * (row - self.mean)

    def _apply(self, obs: np.ndarray) -> np.ndarray:
        if self.mean is None or self.count < 2:
            return obs
        var = self.m2 / max(self.count - 1, 1.0)
        out = (obs - self.mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def on_obs(self, obs: np.ndarray,
               resets: Optional[np.ndarray] = None) -> np.ndarray:
        self._update(obs)
        return self._apply(obs)

    def on_batch(self, batch: SampleBatch) -> SampleBatch:
        # rollout obs were already normalized on_obs; normalize the
        # final-obs column (used for bootstrap values) consistently
        if FINAL_OBS in batch:
            batch[FINAL_OBS] = self._apply(batch[FINAL_OBS])
        return batch

    def get_state(self) -> Dict[str, Any]:
        return {"count": self.count, "mean": self.mean, "m2": self.m2}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]
        # broadcast state is fully-shared history: future deltas are
        # relative to it
        self._snap = (self.count,
                      None if self.mean is None else self.mean.copy(),
                      None if self.m2 is None else self.m2.copy())

    def pop_delta_state(self) -> Dict[str, Any]:
        """Since-last-sync stats via inverse Chan merge against the
        snapshot: total = merge(snapshot, delta) solved for delta."""
        s_count, s_mean, s_m2 = self._snap
        d_count = self.count - s_count
        if d_count <= 0 or self.mean is None:
            return {"count": 0.0, "mean": None, "m2": None}
        if s_mean is None:
            d_mean, d_m2 = self.mean.copy(), self.m2.copy()
        else:
            d_mean = (self.count * self.mean
                      - s_count * s_mean) / d_count
            gap = d_mean - s_mean
            d_m2 = (self.m2 - s_m2
                    - gap ** 2 * (s_count * d_count / self.count))
            np.maximum(d_m2, 0.0, out=d_m2)  # numeric floor
        self._snap = (self.count, self.mean.copy(), self.m2.copy())
        return {"count": d_count, "mean": d_mean, "m2": d_m2}

    def merge_states(self, states: list) -> Dict[str, Any]:
        """Parallel Welford merge (Chan et al.) of per-runner stats."""
        states = [s for s in states if s and s.get("mean") is not None]
        if not states:
            return self.get_state()
        count = states[0]["count"]
        mean = np.array(states[0]["mean"], np.float64)
        m2 = np.array(states[0]["m2"], np.float64)
        for s in states[1:]:
            nb, mb, m2b = s["count"], s["mean"], s["m2"]
            delta = mb - mean
            total = count + nb
            mean = mean + delta * (nb / total)
            m2 = m2 + m2b + delta ** 2 * (count * nb / total)
            count = total
        return {"count": count, "mean": mean, "m2": m2}


class FrameStack(Connector):
    """Concatenate the last k observations along the feature axis
    (reference: rllib/connectors/env_to_module/frame_stacking.py).
    The module's obs_dim must be built k× wider (the algorithm config
    accounts for this via obs_dim_multiplier)."""

    def __init__(self, k: int = 4):
        if k < 1:
            raise ValueError("FrameStack k must be >= 1")
        self.k = k
        self._frames: Optional[deque] = None

    def on_obs(self, obs: np.ndarray,
               resets: Optional[np.ndarray] = None) -> np.ndarray:
        if self._frames is None or self._frames[0].shape != obs.shape:
            self._frames = deque([obs] * self.k, maxlen=self.k)
        else:
            self._frames.append(obs)
            if resets is not None and resets.any():
                # a fresh episode's stack must not contain the dead
                # episode's frames: restart those envs' stacks with
                # k copies of the reset observation
                frames = list(self._frames)
                for j in range(self.k):
                    frame = frames[j].copy()
                    frame[resets] = obs[resets]
                    frames[j] = frame
                self._frames = deque(frames, maxlen=self.k)
        return np.concatenate(list(self._frames), axis=-1)

    def on_batch(self, batch: SampleBatch) -> SampleBatch:
        # FINAL_OBS arrives raw (one frame); the stacked equivalent at
        # step t is the step's stack shifted by one frame with the
        # final frame appended — OBS[t][..., D:] ++ final[t]
        if FINAL_OBS in batch and OBS in batch and self.k > 1:
            raw_dim = batch[FINAL_OBS].shape[-1]
            if batch[OBS].shape[-1] == raw_dim * self.k:
                batch[FINAL_OBS] = np.concatenate(
                    [batch[OBS][..., raw_dim:], batch[FINAL_OBS]],
                    axis=-1)
        return batch

    def obs_dim_multiplier(self) -> int:
        return self.k


class RewardClip(Connector):
    """Clip rewards into [-bound, bound] on the learner path
    (reference: the Atari reward-clipping connector)."""

    def __init__(self, bound: float = 1.0):
        self.bound = bound

    def on_batch(self, batch: SampleBatch) -> SampleBatch:
        if REWARDS in batch:
            batch[REWARDS] = np.clip(batch[REWARDS], -self.bound,
                                     self.bound)
        return batch


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)
        # An obs-widening connector (FrameStack) must come LAST: its
        # on_batch reconstructs stacked FINAL_OBS from the OBS column,
        # which only matches if every other transform already ran —
        # any other position silently corrupts bootstrap values.
        for i, c in enumerate(self.connectors):
            if (c.obs_dim_multiplier() > 1
                    and i != len(self.connectors) - 1):
                raise ValueError(
                    f"{type(c).__name__} widens the observation and "
                    "must be the last connector in the pipeline")

    def on_obs(self, obs: np.ndarray,
               resets: Optional[np.ndarray] = None) -> np.ndarray:
        for c in self.connectors:
            obs = c.on_obs(obs, resets)
        return obs

    def on_batch(self, batch: SampleBatch) -> SampleBatch:
        for c in self.connectors:
            batch = c.on_batch(batch)
        return batch

    def get_state(self) -> Dict[str, Any]:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def merge_states(self, states: list) -> Dict[str, Any]:
        return {i: c.merge_states([s.get(i, {}) for s in states if s])
                for i, c in enumerate(self.connectors)}

    def pop_delta_state(self) -> Dict[str, Any]:
        return {i: c.pop_delta_state()
                for i, c in enumerate(self.connectors)}

    def obs_dim_multiplier(self) -> int:
        out = 1
        for c in self.connectors:
            out *= c.obs_dim_multiplier()
        return out
