"""Algorithm / AlgorithmConfig: the RL driver loop.

Reference: rllib/algorithms/algorithm.py:1190 (step = sample +
training_step + metrics) and algorithm_config.py:109 (fluent builder:
.environment().training().env_runners().learners()). The Trainable
surface (train/save/restore) matches what ray_tpu.tune drives.
"""

from __future__ import annotations

import copy
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.env import Env, JaxEnv, make_env, make_jax_env
from ray_tpu.rl.rl_module import RLModuleSpec
from ray_tpu.rl.sample_batch import SampleBatch


class AlgorithmConfig:
    """Fluent config; subclass per algorithm for defaults."""

    algo_class = None  # set by subclasses

    def __init__(self):
        # environment
        self.env: Any = None
        self.env_creator: Optional[Callable[[], Env]] = None
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 8
        self.rollout_fragment_length: int = 128
        self.prefer_jax_env: bool = True
        # training
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 1024
        self.grad_clip: Optional[float] = None
        # learners
        self.num_learners: int = 0
        # module
        self.hidden: Tuple[int, ...] = (64, 64)
        # env→module connectors: FACTORIES (each runner builds its own
        # stateful pipeline; see ray_tpu/rl/connectors.py)
        self.connector_factories: list = []
        # evaluation-runner split (reference: algorithm.py:1407 evaluate
        # + evaluation_config): separate runners, exploit-mode policy,
        # metrics reported under the "evaluation" key
        self.evaluation_interval: Optional[int] = None
        self.evaluation_duration: int = 10  # episodes per evaluate()
        self.evaluation_num_envs: int = 4
        # multi-agent (reference: algorithm_config.py multi_agent():
        # policies + policy_mapping_fn + policies_to_train)
        self.policies: Optional[Dict[str, Any]] = None
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        self.policies_to_train: Optional[List[str]] = None
        # misc
        self.seed: int = 0

    # -- fluent sections (reference: algorithm_config.py builder) -------
    def environment(self, env=None, *, env_creator=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None,
                    prefer_jax_env=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if prefer_jax_env is not None:
            self.prefer_jax_env = prefer_jax_env
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def learners(self, *, num_learners=None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def rl_module(self, *, hidden=None) -> "AlgorithmConfig":
        if hidden is not None:
            self.hidden = tuple(hidden)
        return self

    def evaluation(self, *, evaluation_interval=None,
                   evaluation_duration=None,
                   evaluation_num_envs=None) -> "AlgorithmConfig":
        """Evaluation-runner split (reference: algorithm.py:1407
        evaluate; evaluation_interval in iterations, duration in
        episodes)."""
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        if evaluation_num_envs is not None:
            self.evaluation_num_envs = evaluation_num_envs
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    policies_to_train=None) -> "AlgorithmConfig":
        """Multi-agent setup (reference: algorithm_config.py
        multi_agent()). ``policies`` maps policy id -> (obs_space,
        action_space) or None to infer from the first mapped agent;
        ``policy_mapping_fn(agent_id) -> policy_id``."""
        if policies is not None:
            self.policies = (dict.fromkeys(policies)
                             if not isinstance(policies, dict)
                             else dict(policies))
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if policies_to_train is not None:
            self.policies_to_train = list(policies_to_train)
        return self

    @property
    def is_multi_agent(self) -> bool:
        return self.policy_mapping_fn is not None

    def make_multi_agent_env(self):
        from ray_tpu.rl.multi_agent import MultiAgentEnv
        if self.env_creator is not None:
            return self.env_creator()
        if isinstance(self.env, type) and issubclass(self.env,
                                                     MultiAgentEnv):
            return self.env()
        raise ValueError(
            f"multi-agent config needs an env_creator or a "
            f"MultiAgentEnv class, got {self.env!r}")

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # -- env construction ----------------------------------------------
    def make_python_env(self) -> Env:
        if self.env_creator is not None:
            return self.env_creator()
        if isinstance(self.env, str):
            return make_env(self.env)
        if isinstance(self.env, type) and issubclass(self.env, Env):
            return self.env()
        raise ValueError(f"cannot build env from {self.env!r}")

    def make_jax_env(self) -> Optional[JaxEnv]:
        if not self.prefer_jax_env:
            return None
        if isinstance(self.env, str):
            return make_jax_env(self.env)
        if isinstance(self.env, type) and issubclass(self.env, JaxEnv):
            return self.env()
        if isinstance(self.env, JaxEnv):
            return self.env
        return None

    def env_to_module(self, connectors: list) -> "AlgorithmConfig":
        """Configure the env→module connector pipeline (reference:
        AlgorithmConfig.env_to_module_connector). Pass factories
        (zero-arg callables) so every env runner gets its own state."""
        self.connector_factories = list(connectors)
        return self

    def build_connectors(self):
        if not self.connector_factories:
            return None
        from ray_tpu.rl.connectors import ConnectorPipeline
        return ConnectorPipeline([f() for f in self.connector_factories])

    def module_spec(self) -> RLModuleSpec:
        env = self.make_jax_env() or self.make_python_env()
        obs_space = env.observation_space
        pipeline = self.build_connectors()
        if pipeline is not None:
            mult = pipeline.obs_dim_multiplier()
            if mult > 1:  # e.g. FrameStack widens the module's input
                from ray_tpu.rl.spaces import Box
                lo = np.tile(np.broadcast_to(
                    obs_space.low, obs_space.shape).ravel(), mult)
                hi = np.tile(np.broadcast_to(
                    obs_space.high, obs_space.shape).ravel(), mult)
                obs_space = Box(lo.astype(np.float32),
                                hi.astype(np.float32))
        return RLModuleSpec(obs_space=obs_space,
                            action_space=env.action_space,
                            hidden=self.hidden)

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build_algo(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use a subclass "
                             "like PPOConfig")
        return self.algo_class(self.copy())

    # legacy alias (reference keeps .build() working)
    build = build_algo


class Algorithm:
    """Iteration-driven trainer; also a Tune trainable surface."""

    # Subclasses that consume config.multi_agent() set this; everything
    # else fails at build time instead of mis-running a MultiAgentEnv
    # through the single-agent path.
    supports_multi_agent = False

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._env_steps_lifetime = 0
        self._episode_returns: List[float] = []
        self._episode_lens: List[int] = []
        if (config.evaluation_interval
                and type(self).evaluate is Algorithm.evaluate):
            # Fail at build time, not at iteration N mid-job.
            raise ValueError(
                f"{type(self).__name__} does not implement evaluate(); "
                "remove evaluation_interval from the config")
        if config.is_multi_agent and not self.supports_multi_agent:
            raise ValueError(
                f"{type(self).__name__} does not support multi_agent(); "
                "use PPO, or drop the policy_mapping_fn")
        self.setup(config)

    # -- subclass hooks --------------------------------------------------
    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        """One unit of sampling + learning; returns metrics."""
        raise NotImplementedError

    # -- public loop -----------------------------------------------------
    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        steps_before = self._env_steps_lifetime
        metrics = self.training_step()
        self.iteration += 1
        elapsed = time.perf_counter() - start
        sampled = self._env_steps_lifetime - steps_before
        recent = self._episode_returns[-100:]
        recent_lens = self._episode_lens[-100:]
        result = {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": sampled,
            "num_env_steps_sampled_lifetime": self._env_steps_lifetime,
            "env_steps_per_sec": sampled / max(elapsed, 1e-9),
            "time_this_iter_s": elapsed,
            "episode_return_mean": (float(np.mean(recent)) if recent
                                    else float("nan")),
            "episode_len_mean": (float(np.mean(recent_lens))
                                 if recent_lens else float("nan")),
            "episodes_total": len(self._episode_returns),
        }
        result.update(metrics)
        if (self.config.evaluation_interval
                and self.iteration % self.config.evaluation_interval == 0):
            result["evaluation"] = self.evaluate()
        return result

    def evaluate(self) -> Dict[str, Any]:
        """Run the evaluation-runner split (reference:
        algorithm.py:1407). Subclasses with evaluation support override."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement evaluate()")

    def record_episodes(self, returns: List[float],
                        lens: Optional[List[int]] = None) -> None:
        self._episode_returns.extend(returns)
        if lens:
            self._episode_lens.extend(lens)

    # -- checkpointing (reference: rllib/utils/checkpoints.py
    #    Checkpointable.save_to_path / restore_from_path) ----------------
    def get_state(self) -> Dict[str, Any]:
        return {
            "iteration": self.iteration,
            "env_steps_lifetime": self._env_steps_lifetime,
            "episode_returns": self._episode_returns[-1000:],
            "episode_lens": self._episode_lens[-1000:],
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.iteration = state["iteration"]
        self._env_steps_lifetime = state["env_steps_lifetime"]
        self._episode_returns = list(state["episode_returns"])
        self._episode_lens = list(state.get("episode_lens", ()))

    def save_to_path(self, path: str) -> str:
        from ray_tpu.core import serialization
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            f.write(serialization.dumps(self.get_state()))
        return path

    def restore_from_path(self, path: str) -> None:
        from ray_tpu.core import serialization
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            self.set_state(serialization.loads(f.read()))

    def stop(self) -> None:
        pass
