"""Multi-agent RL: envs, module dicts, and the multi-agent env runner.

Reference surface: rllib/env/multi_agent_env.py:33 (MultiAgentEnv —
per-agent obs/action dicts, "__all__" termination),
rllib/core/rl_module/multi_rl_module.py:40 (module dict keyed by
module_id), and the policy-mapping seam
(AlgorithmConfig.multi_agent(policies=..., policy_mapping_fn=...)).

Two runners:
- MultiAgentEnvRunner targets PARALLEL envs — every agent observes and
  acts at every step (the PettingZoo parallel-env shape), so per-module
  streams are dense [T, S] columns.
- TurnBasedEnvRunner targets TURN-BASED envs — each step's obs dict
  names exactly the agents that must act now (the reference's
  MultiAgentEnv supports agents acting on different steps via episode
  bookkeeping; rllib/env/multi_agent_env.py:33). Per-(env, agent)
  transition streams are assembled with deferred reward credit (an
  action's reward is everything the agent receives until its next
  observation) and carried over between sample() calls so the emitted
  columns are still dense [T, S] — the same GAE/learner path consumes
  them unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.rl_module import RLModuleSpec
from ray_tpu.rl.sample_batch import (
    ACTIONS, DONES, FINAL_OBS, LOGP, OBS, REWARDS, TRUNCATEDS, VF_PREDS,
    SampleBatch)
from ray_tpu.rl.spaces import Box, Discrete, Space


class MultiAgentEnv:
    """Parallel multi-agent env (reference: multi_agent_env.py:33).

    ``step`` takes/returns per-agent dicts; the termination dict carries
    the reference's ``"__all__"`` key marking episode end for everyone.
    """

    agents: List[str]
    observation_spaces: Dict[str, Space]
    action_spaces: Dict[str, Space]
    max_episode_steps: int = 10_000

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        """-> (obs, rewards, terminateds, truncateds, infos) dicts;
        terminateds/truncateds include "__all__"."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class RepeatedRockPaperScissors(MultiAgentEnv):
    """Two-player zero-sum repeated rock-paper-scissors (the canonical
    rllib competitive example: rllib/examples/envs/classes/
    rock_paper_scissors.py). Observation = one-hot of both players'
    previous moves (zeros on the first step)."""

    agents = ["player_0", "player_1"]
    max_episode_steps = 10

    _WIN = {(0, 2), (1, 0), (2, 1)}  # rock>scissors, paper>rock, scissors>paper

    def __init__(self, episode_len: int = 10):
        self.max_episode_steps = episode_len
        obs_space = Box(np.zeros(6, np.float32), np.ones(6, np.float32))
        self.observation_spaces = {a: obs_space for a in self.agents}
        self.action_spaces = {a: Discrete(3) for a in self.agents}
        self._t = 0
        self._last = None

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for idx, agent in enumerate(self.agents):
            vec = np.zeros(6, np.float32)
            if self._last is not None:
                mine, theirs = self._last[idx], self._last[1 - idx]
                vec[mine] = 1.0
                vec[3 + theirs] = 1.0
            out[agent] = vec
        return out

    def reset(self, *, seed: Optional[int] = None):
        self._t = 0
        self._last = None
        return self._obs(), {a: {} for a in self.agents}

    def step(self, action_dict):
        a0 = int(action_dict["player_0"])
        a1 = int(action_dict["player_1"])
        self._last = (a0, a1)
        self._t += 1
        if (a0, a1) in self._WIN:
            r0, r1 = 1.0, -1.0
        elif (a1, a0) in self._WIN:
            r0, r1 = -1.0, 1.0
        else:
            r0 = r1 = 0.0
        done = self._t >= self.max_episode_steps
        rewards = {"player_0": r0, "player_1": r1}
        terminateds = {a: False for a in self.agents}
        terminateds["__all__"] = False
        truncateds = {a: done for a in self.agents}
        truncateds["__all__"] = done
        return (self._obs(), rewards, terminateds, truncateds,
                {a: {} for a in self.agents})


class TicTacToe(MultiAgentEnv):
    """Turn-based tic-tac-toe (reference: the turn-based MultiAgentEnv
    pattern, e.g. rllib/examples/envs/classes/tic_tac_toe.py): only the
    agent to move appears in the obs dict. Observation = 9 cells from
    the mover's perspective (+1 mine, -1 theirs, 0 empty) + 9-dim legal
    mask. Illegal moves lose immediately (standard rllib example
    semantics). Win +1 / loss -1 for both sides at the terminal step."""

    agents = ["player_x", "player_o"]
    turn_based = True
    max_episode_steps = 9

    def __init__(self):
        obs_space = Box(-1.0, 1.0, (18,))
        act_space = Discrete(9)
        self.observation_spaces = {a: obs_space for a in self.agents}
        self.action_spaces = {a: act_space for a in self.agents}

    def _obs_for(self, agent: str) -> np.ndarray:
        sign = 1 if agent == "player_x" else -1
        cells = (self.board * sign).astype(np.float32)
        legal = (self.board == 0).astype(np.float32)
        return np.concatenate([cells, legal])

    def reset(self, *, seed: Optional[int] = None):
        # deterministic env: seed accepted for API uniformity only
        self.board = np.zeros(9, dtype=np.int8)
        self.to_move = 0  # X starts
        return {self.agents[0]: self._obs_for(self.agents[0])}, {}

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def _winner(self) -> int:
        for a, b, c in self._LINES:
            s = self.board[a] + self.board[b] + self.board[c]
            if s == 3:
                return 1
            if s == -3:
                return -1
        return 0

    def step(self, action_dict: Dict[str, Any]):
        mover = self.agents[self.to_move]
        other = self.agents[1 - self.to_move]
        action = int(action_dict[mover])
        sign = 1 if mover == "player_x" else -1
        if self.board[action] != 0:
            # illegal: mover loses on the spot
            rewards = {mover: -1.0, other: 1.0}
            return ({}, rewards, {"__all__": True}, {"__all__": False},
                    {})
        self.board[action] = sign
        win = self._winner()
        if win != 0:
            rewards = {mover: 1.0, other: -1.0}
            return ({}, rewards, {"__all__": True}, {"__all__": False},
                    {})
        if not (self.board == 0).any():
            return ({}, {mover: 0.0, other: 0.0}, {"__all__": True},
                    {"__all__": False}, {})
        self.to_move = 1 - self.to_move
        nxt = self.agents[self.to_move]
        return ({nxt: self._obs_for(nxt)}, {mover: 0.0, other: 0.0},
                {"__all__": False}, {"__all__": False}, {})


class _MultiAgentRunnerBase:
    """Shared plumbing for the parallel and turn-based runners: env
    fleet, module specs + policy mapping, per-module params and jitted
    act fns, weight sync, and episode-metric bookkeeping (one contract,
    two sampling disciplines — PPO swaps the subclasses freely)."""

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 module_specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn: Callable[[str], str], *,
                 num_envs: int = 1, rollout_len: int = 64, seed: int = 0,
                 explore: bool = True):
        import jax
        self.envs = [env_creator() for _ in range(num_envs)]
        self.specs = module_specs
        self.rollout_len = rollout_len
        self.explore = explore
        self._key = jax.random.PRNGKey(seed)

        env0 = self.envs[0]
        self.agents = list(env0.agents)
        self.mapping = {a: policy_mapping_fn(a) for a in self.agents}
        unknown = set(self.mapping.values()) - set(module_specs)
        if unknown:
            raise ValueError(
                f"policy_mapping_fn maps to unknown module(s) {unknown}; "
                f"configured modules: {sorted(module_specs)}")
        # Streams: one per (env, agent), grouped by module.
        self.streams: Dict[str, List[Tuple[int, str]]] = {
            mid: [] for mid in module_specs}
        for i in range(num_envs):
            for agent in self.agents:
                self.streams[self.mapping[agent]].append((i, agent))

        self.params = {
            mid: jax.tree.map(np.asarray,
                              spec.init(jax.random.PRNGKey(seed + j)))
            for j, (mid, spec) in enumerate(sorted(module_specs.items()))}
        self._obs = [env.reset(seed=seed + i)[0]
                     for i, env in enumerate(self.envs)]
        self._ep_return = {(i, a): 0.0 for i in range(num_envs)
                           for a in self.agents}
        self._ep_len = np.zeros(num_envs, dtype=np.int64)
        self._completed: List[float] = []           # per-episode sum
        self._completed_lens: List[int] = []
        self._completed_by_module: Dict[str, List[float]] = {
            mid: [] for mid in module_specs}

        def make_act(spec):
            def _act(params, obs, key):
                dist, value = spec.forward(params, obs)
                action = dist.sample(key) if explore else dist.mode()
                return action, dist.log_prob(action), value
            return jax.jit(_act)

        self._act = {mid: make_act(spec)
                     for mid, spec in module_specs.items()}

    def set_weights(self, params_by_module: Dict[str, Any]) -> None:
        import jax
        for mid, params in params_by_module.items():
            self.params[mid] = jax.tree.map(np.asarray, params)

    def _reset_metrics(self) -> None:
        for key in self._ep_return:
            self._ep_return[key] = 0.0
        self._ep_len[:] = 0
        self._completed = []
        self._completed_lens = []
        self._completed_by_module = {mid: [] for mid in self.specs}

    def reset_envs(self) -> None:
        """Fresh episodes + cleared accumulators (see
        SingleAgentEnvRunner.reset_envs)."""
        self._obs = [env.reset()[0] for env in self.envs]
        self._reset_metrics()

    def pop_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_returns": self._completed,
            "episode_lens": self._completed_lens,
            "module_returns": {mid: vals for mid, vals
                               in self._completed_by_module.items()},
        }
        self._completed = []
        self._completed_lens = []
        self._completed_by_module = {mid: [] for mid in self.specs}
        return out

    def ping(self) -> bool:
        return True


class MultiAgentEnvRunner(_MultiAgentRunnerBase):
    """Vectorized sampler over parallel MultiAgentEnvs.

    Experiences are grouped by module: ``policy_mapping_fn(agent_id)``
    names the module an agent's stream feeds, and sample() returns
    ``{module_id: [T, S] columns}`` where S = (num_envs x agents mapped
    to that module) — the exact shape the single-agent learner path
    already consumes (reference: multi-agent EnvRunner producing
    MultiAgentBatch keyed by module_id).
    """

    # -- sampling --------------------------------------------------------
    def _stacked_obs(self, mid: str) -> np.ndarray:
        return np.stack([self._obs[i][agent]
                         for i, agent in self.streams[mid]])

    def sample(self) -> Dict[str, SampleBatch]:
        import jax
        T = self.rollout_len
        cols: Dict[str, Dict[str, list]] = {
            mid: {k: [] for k in (OBS, ACTIONS, LOGP, VF_PREDS, REWARDS,
                                  DONES, TRUNCATEDS, FINAL_OBS)}
            for mid in self.specs}
        for _ in range(T):
            actions_by_env: List[Dict[str, Any]] = [
                {} for _ in range(len(self.envs))]
            per_mid_step: Dict[str, Dict[str, np.ndarray]] = {}
            for mid in self.specs:
                obs = self._stacked_obs(mid)
                self._key, sub = jax.random.split(self._key)
                action, logp, value = self._act[mid](
                    self.params[mid], obs, sub)
                action = np.asarray(action)
                per_mid_step[mid] = {
                    OBS: obs, ACTIONS: action,
                    LOGP: np.asarray(logp), VF_PREDS: np.asarray(value)}
                for s, (i, agent) in enumerate(self.streams[mid]):
                    actions_by_env[i][agent] = action[s]

            step_out = []
            for i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(actions_by_env[i])
                done = bool(term.get("__all__")) or bool(
                    trunc.get("__all__"))
                self._ep_len[i] += 1
                for agent in self.agents:
                    self._ep_return[(i, agent)] += float(
                        rew.get(agent, 0.0))
                final = obs  # true next obs, pre-reset
                if done:
                    ep_sum = sum(self._ep_return[(i, a)]
                                 for a in self.agents)
                    self._completed.append(float(ep_sum))
                    self._completed_lens.append(int(self._ep_len[i]))
                    for agent in self.agents:
                        self._completed_by_module[
                            self.mapping[agent]].append(
                            float(self._ep_return[(i, agent)]))
                        self._ep_return[(i, agent)] = 0.0
                    self._ep_len[i] = 0
                    obs, _ = env.reset()
                self._obs[i] = obs
                step_out.append((final, rew, term, trunc, done))

            for mid in self.specs:
                streams = self.streams[mid]
                n = len(streams)
                rewards = np.zeros(n, np.float32)
                dones = np.zeros(n, bool)
                truncs = np.zeros(n, bool)
                finals = np.stack([step_out[i][0][agent]
                                   for i, agent in streams])
                for s, (i, agent) in enumerate(streams):
                    _, rew, term, trunc, done = step_out[i]
                    rewards[s] = rew.get(agent, 0.0)
                    agent_term = bool(term.get(agent)) or bool(
                        term.get("__all__"))
                    agent_trunc = bool(trunc.get(agent)) or bool(
                        trunc.get("__all__"))
                    dones[s] = done or agent_term or agent_trunc
                    truncs[s] = agent_trunc and not agent_term
                c = cols[mid]
                c[OBS].append(per_mid_step[mid][OBS])
                c[ACTIONS].append(per_mid_step[mid][ACTIONS])
                c[LOGP].append(per_mid_step[mid][LOGP])
                c[VF_PREDS].append(per_mid_step[mid][VF_PREDS])
                c[REWARDS].append(rewards)
                c[DONES].append(dones)
                c[TRUNCATEDS].append(truncs)
                c[FINAL_OBS].append(finals)

        out: Dict[str, SampleBatch] = {}
        for mid, c in cols.items():
            batch = SampleBatch({k: np.stack(v) for k, v in c.items()})
            batch["bootstrap_value"] = np.asarray(
                self.specs[mid].compute_values(
                    self.params[mid], self._stacked_obs(mid)))
            out[mid] = batch
        return out


class TurnBasedEnvRunner(_MultiAgentRunnerBase):
    """Sampler for turn-based MultiAgentEnvs (acting set varies per
    step; reference: rllib's episode-based multi-agent bookkeeping).

    Credit assignment: an agent's transition opens when it acts and
    closes at its NEXT observation (or episode end), its reward being
    everything received in between — the standard turn-based fold
    (opponent replies count toward the action that provoked them).
    sample() steps the envs until every (env, agent) stream holds
    ``rollout_len`` closed transitions (surplus carries over to the
    next call), so the emitted columns are dense [T, S] and the
    single-agent GAE/learner path consumes them unchanged.

    Note: the jitted per-module forward recompiles per distinct acting
    batch size; for alternating-move games that size is constant
    (#envs), so steady state is one compile per module.
    """

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 module_specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn: Callable[[str], str], *,
                 num_envs: int = 1, rollout_len: int = 64, seed: int = 0,
                 explore: bool = True):
        super().__init__(env_creator, module_specs, policy_mapping_fn,
                         num_envs=num_envs, rollout_len=rollout_len,
                         seed=seed, explore=explore)
        # open transition per (env, agent): [obs, action, logp, vf,
        # reward-so-far]; closed transitions buffer per (env, agent)
        self._open: Dict[Tuple[int, str], Optional[list]] = {
            (i, a): None for i in range(num_envs) for a in self.agents}
        self._closed: Dict[Tuple[int, str], List[tuple]] = {
            (i, a): [] for i in range(num_envs) for a in self.agents}
        self.env_steps_last_sample = 0

    def _close(self, key: Tuple[int, str], final_obs, done: bool,
               trunc: bool) -> None:
        open_t = self._open[key]
        if open_t is None:
            return
        obs, action, logp, vf, reward = open_t
        self._closed[key].append(
            (obs, action, logp, vf, reward, done, trunc, final_obs))
        self._open[key] = None

    def _quota_met(self) -> bool:
        return all(len(buf) >= self.rollout_len
                   for buf in self._closed.values())

    def sample(self) -> Dict[str, SampleBatch]:
        import jax
        self.env_steps_last_sample = 0
        guard = 0
        max_steps = (self.rollout_len * len(self.agents) + 64) * 64
        while not self._quota_met():
            guard += 1
            if guard > max_steps:
                raise RuntimeError(
                    "turn-based sampling stalled: some agent never got "
                    f"{self.rollout_len} turns in {max_steps} env steps "
                    "(does every agent keep acting in this env?)")
            # group acting agents by module across envs
            acting: Dict[str, List[Tuple[int, str]]] = {
                mid: [] for mid in self.specs}
            for i in range(len(self.envs)):
                for agent in self._obs[i]:
                    acting[self.mapping[agent]].append((i, agent))
            actions_by_env: List[Dict[str, Any]] = [
                {} for _ in range(len(self.envs))]
            for mid, streams in acting.items():
                if not streams:
                    continue
                obs = np.stack([self._obs[i][agent]
                                for i, agent in streams])
                self._key, sub = jax.random.split(self._key)
                action, logp, value = self._act[mid](
                    self.params[mid], obs, sub)
                action = np.asarray(action)
                logp = np.asarray(logp)
                value = np.asarray(value)
                for s, (i, agent) in enumerate(streams):
                    actions_by_env[i][agent] = action[s]
                    # acting implies the previous open transition for
                    # this agent was closed when this obs arrived
                    self._open[(i, agent)] = [
                        obs[s], action[s], logp[s], value[s], 0.0]

            for i, env in enumerate(self.envs):
                if not actions_by_env[i]:
                    continue
                self.env_steps_last_sample += 1
                obs, rew, term, trunc, _ = env.step(actions_by_env[i])
                done = bool(term.get("__all__")) or bool(
                    trunc.get("__all__"))
                self._ep_len[i] += 1
                for agent in self.agents:
                    r = float(rew.get(agent, 0.0))
                    self._ep_return[(i, agent)] += r
                    open_t = self._open[(i, agent)]
                    if open_t is not None:
                        open_t[4] += r
                if done:
                    all_trunc = bool(trunc.get("__all__")) and not bool(
                        term.get("__all__"))
                    for agent in self.agents:
                        key = (i, agent)
                        # terminal: close every open transition; final
                        # obs only matters under truncation (bootstrap)
                        fallback = (self._open[key][0]
                                    if self._open[key] is not None
                                    else None)
                        final = obs.get(agent, fallback)
                        agent_trunc = (bool(trunc.get(agent))
                                       or all_trunc)
                        self._close(key, final, True, agent_trunc)
                        self._completed_by_module[
                            self.mapping[agent]].append(
                            float(self._ep_return[key]))
                    ep_sum = sum(self._ep_return[(i, a)]
                                 for a in self.agents)
                    self._completed.append(float(ep_sum))
                    self._completed_lens.append(int(self._ep_len[i]))
                    for agent in self.agents:
                        self._ep_return[(i, agent)] = 0.0
                    self._ep_len[i] = 0
                    obs, _ = env.reset()
                else:
                    # agents observing now close their previous turn
                    for agent in obs:
                        self._close((i, agent), obs[agent], False,
                                    False)
                self._obs[i] = obs

        out: Dict[str, SampleBatch] = {}
        T = self.rollout_len
        for mid, streams in self.streams.items():
            taken = []
            for key in streams:
                taken.append(self._closed[key][:T])
                # Carry over the surplus, BOUNDED: with agents acting
                # at very different rates the fast streams outpace the
                # T-per-sample drain; keep the newest 4T (dropping
                # oldest whole transitions trades a GAE seam at the
                # drop point for bounded memory and fresher data).
                self._closed[key] = self._closed[key][T:][-4 * T:]
            # [T, S] time-major stacking, column by column
            def col(j, dtype=None):
                arr = np.stack(
                    [np.stack([taken[s][t][j] for s in
                               range(len(streams))])
                     for t in range(T)])
                return arr.astype(dtype) if dtype is not None else arr
            batch = SampleBatch({
                OBS: col(0), ACTIONS: col(1), LOGP: col(2, np.float32),
                VF_PREDS: col(3, np.float32),
                REWARDS: col(4, np.float32), DONES: col(5, bool),
                TRUNCATEDS: col(6, bool), FINAL_OBS: col(7)})
            # per-stream bootstrap from the last taken final obs (GAE
            # cuts it when the last transition ended an episode)
            last_final = np.stack(
                [taken[s][-1][7] for s in range(len(streams))])
            batch["bootstrap_value"] = np.asarray(
                self.specs[mid].compute_values(
                    self.params[mid], last_final))
            out[mid] = batch
        return out

    def reset_envs(self) -> None:
        super().reset_envs()
        for key in self._ep_return:
            self._open[key] = None
            self._closed[key] = []


def infer_module_specs(env: MultiAgentEnv,
                       policy_mapping_fn: Callable[[str], str],
                       policies: Optional[Dict[str, Any]] = None,
                       hidden: Tuple[int, ...] = (64, 64)
                       ) -> Dict[str, RLModuleSpec]:
    """Module specs per policy id: explicit (obs_space, action_space)
    pairs win; otherwise inferred from the first agent mapped to each
    module (reference: MultiRLModuleSpec inference in
    AlgorithmConfig.get_multi_rl_module_spec)."""
    specs: Dict[str, RLModuleSpec] = {}
    for agent in env.agents:
        mid = policy_mapping_fn(agent)
        if mid in specs:
            continue
        if policies and policies.get(mid) is not None:
            obs_space, act_space = policies[mid]
        else:
            obs_space = env.observation_spaces[agent]
            act_space = env.action_spaces[agent]
        specs[mid] = RLModuleSpec(obs_space=obs_space,
                                  action_space=act_space, hidden=hidden)
    if policies:
        for mid in policies:
            if mid not in specs:
                raise ValueError(
                    f"policy {mid!r} has no agent mapped to it by "
                    "policy_mapping_fn")
    return specs
