"""ray_tpu.rl — reinforcement learning (reference: rllib/).

Algorithm/EnvRunner/Learner stack re-shaped for TPU: rollouts over
JAX functional envs compile to one `lax.scan` program, learners are
pure-JAX with GSPMD data parallelism in-mesh and a host-collective
gradient allreduce across learner actors.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.algorithms.appo import APPO, APPOConfig
from ray_tpu.rl.algorithms.bc import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig
from ray_tpu.rl.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rl.algorithms.iql import IQL, IQLConfig
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rl.algorithms.sac import SAC, SACConfig
from ray_tpu.rl.connectors import (
    Connector, ConnectorPipeline, FrameStack, ObsNormalizer, RewardClip)
from ray_tpu.rl.offline import OfflineData, collect_episodes
from ray_tpu.rl.env import (
    CartPole, CartPoleJax, Env, JaxEnv, Pendulum, make_env, register_env)
from ray_tpu.rl.env_runner import JaxEnvRunner, SingleAgentEnvRunner
from ray_tpu.rl.learner import Learner, LearnerGroup, compute_gae
from ray_tpu.rl.multi_agent import (
    MultiAgentEnv, MultiAgentEnvRunner, RepeatedRockPaperScissors,
    TicTacToe, TurnBasedEnvRunner)
from ray_tpu.rl.rl_module import RLModuleSpec
from ray_tpu.rl.sample_batch import SampleBatch, concat_samples
from ray_tpu.rl import spaces

__all__ = [
    "APPO", "APPOConfig", "Algorithm", "AlgorithmConfig", "BC", "BCConfig", "CartPole",
    "CQL", "CQLConfig", "CartPoleJax", "Connector", "DreamerV3",
    "DreamerV3Config", "ConnectorPipeline", "DQN", "DQNConfig",
    "Env", "FrameStack", "IMPALA", "IMPALAConfig", "IQL",
    "IQLConfig", "JaxEnv",
    "JaxEnvRunner", "Learner",
    "LearnerGroup", "MARWIL", "MARWILConfig", "MultiAgentEnv",
    "MultiAgentEnvRunner", "ObsNormalizer",
    "OfflineData", "PPO", "PPOConfig", "Pendulum", "RLModuleSpec",
    "RepeatedRockPaperScissors", "RewardClip", "SAC", "SACConfig",
    "SampleBatch",
    "SingleAgentEnvRunner", "TicTacToe", "TurnBasedEnvRunner",
    "collect_episodes", "compute_gae",
    "concat_samples", "make_env", "register_env", "spaces",
]
