"""Shared evaluation rollouts for algorithms with bespoke policies.

PPO's evaluation-runner split reuses its env runners; value-based /
off-policy algorithms (DQN, SAC) have their own networks, so their
``evaluate()`` implementations share this one exploit-mode episode
loop instead (reference: rllib/algorithms/algorithm.py:1407 evaluate —
dedicated rollouts with exploration off, metrics reported under the
"evaluation" key).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np


def evaluate_policy(env_creator: Callable[[], Any],
                    act_fn: Callable[[Any], Any], *,
                    num_episodes: int = 10,
                    max_steps: int = 10_000) -> Dict[str, Any]:
    """Run ``num_episodes`` greedy episodes; ``act_fn(obs) -> action``."""
    returns: List[float] = []
    lengths: List[int] = []
    env = env_creator()
    try:
        for _ in range(num_episodes):
            obs, _ = env.reset()
            total, steps = 0.0, 0
            for _ in range(max_steps):
                obs, reward, terminated, truncated, _ = env.step(
                    act_fn(obs))
                total += float(reward)
                steps += 1
                if terminated or truncated:
                    break
            returns.append(total)
            lengths.append(steps)
    finally:
        env.close()
    return {
        "episode_return_mean": float(np.mean(returns)),
        "episode_len_mean": float(np.mean(lengths)),
        "episodes_this_eval": len(returns),
    }
