"""Learner / LearnerGroup: the gradient side of the RL stack.

Reference: rllib/core/learner/learner.py:112 (loss + update over an
RLModule) and learner_group.py:101 — the "learner-group allreduce path"
named in BASELINE.json, where N learner actors wrap the module in torch
DDP and allreduce gradients over NCCL.

TPU-native shape:
- Within one host/slice, data parallelism is NOT an allreduce the
  framework runs: the jitted update reads a batch sharded over the
  mesh's `data` axis and XLA inserts the psum over ICI (GSPMD).
- Across learner *actors* (multi-host without a shared mesh), gradients
  are packed into one flat vector (`ravel_pytree`) and allreduced
  through the host collective (ray_tpu.parallel.collective) — one
  exchange per update, the DDP-equivalent control path.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.rl_module import RLModuleSpec


def compute_gae(rewards, values, dones, bootstrap_value, *,
                gamma: float = 0.99, lambda_: float = 0.95):
    """Generalized advantage estimation over time-major [T, N] columns.

    Auto-reset envs: `dones[t]` marks that the transition at t ended an
    episode, so the bootstrap chain is cut there. Returns
    (advantages [T, N], value_targets [T, N]); jit/grad-safe.
    Reference analog: rllib/evaluation/postprocessing.py compute_advantages.
    """
    import jax
    import jax.numpy as jnp

    def scan_fn(next_adv, inp):
        reward, value, done, next_value = inp
        nonterminal = 1.0 - done.astype(jnp.float32)
        delta = reward + gamma * next_value * nonterminal - value
        adv = delta + gamma * lambda_ * nonterminal * next_adv
        return adv, adv

    next_values = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    _, advantages = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (rewards, values, dones, next_values), reverse=True)
    return advantages, advantages + values


def mean_metrics(all_metrics: List[Dict[str, Any]]) -> Dict[str, float]:
    """Average a list of per-update metric dicts (host floats)."""
    return {k: float(np.mean([float(np.asarray(m[k]))
                              for m in all_metrics]))
            for k in all_metrics[0]}


class Learner:
    """Holds params + optimizer; subclasses define `loss`."""

    def __init__(self, module_spec: RLModuleSpec, *,
                 optimizer=None, lr: float = 3e-4, seed: int = 0,
                 grad_clip: Optional[float] = None,
                 collective_group: Optional[str] = None,
                 mesh=None):
        import jax
        import optax

        self.spec = module_spec
        self.mesh = mesh
        self.collective_group = collective_group
        if optimizer is None:
            tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
            optimizer = optax.chain(*tx, optax.adam(lr, eps=1e-5))
        self.optimizer = optimizer
        self.params = module_spec.init(jax.random.PRNGKey(seed))
        self.opt_state = optimizer.init(self.params)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            replicated = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, replicated)
            self.opt_state = jax.device_put(self.opt_state, replicated)
            self._batch_sharding = NamedSharding(mesh, P("data"))
        else:
            self._batch_sharding = None

        def grads_fn(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self.loss, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            import optax as _optax
            return _optax.apply_updates(params, updates), opt_state

        def full_step(params, opt_state, batch):
            grads, metrics = grads_fn(params, batch)
            params, opt_state = apply_fn(params, opt_state, grads)
            return params, opt_state, metrics

        self._grads_fn = jax.jit(grads_fn)
        self._apply_fn = jax.jit(apply_fn)
        self._full_step = jax.jit(full_step)

    # -- subclass hook --------------------------------------------------
    def loss(self, params, batch) -> Tuple[Any, Dict[str, Any]]:
        """(loss scalar, metrics dict). Traced under jit."""
        raise NotImplementedError

    # -- update ---------------------------------------------------------
    def shard_batch(self, batch):
        """Move a host batch to device, sharded over the data axis when
        a mesh is configured (GSPMD inserts the grad psum over ICI)."""
        import jax
        if self._batch_sharding is None:
            return batch
        return jax.device_put(dict(batch), self._batch_sharding)

    def update(self, batch) -> Dict[str, Any]:
        # SampleBatch (dict subclass) isn't a pytree; shard_batch also
        # lays the batch out over the mesh's data axis when configured.
        batch = self.shard_batch(dict(batch))
        if self.collective_group is None:
            self.params, self.opt_state, metrics = self._full_step(
                self.params, self.opt_state, batch)
            return metrics
        # cross-actor DDP: allreduce one packed gradient vector
        import jax
        from jax.flatten_util import ravel_pytree
        from ray_tpu.parallel import collective

        grads, metrics = self._grads_fn(self.params, batch)
        flat, unravel = ravel_pytree(grads)
        world = collective.get_collective_group_size(self.collective_group)
        reduced = collective.allreduce(
            np.asarray(flat), group_name=self.collective_group) / world
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, unravel(reduced))
        return metrics

    def get_weights(self):
        import jax
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, params) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, params)

    def get_state(self) -> Dict[str, Any]:
        import jax
        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        import jax
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            jnp.asarray, state["opt_state"],
            is_leaf=lambda x: isinstance(x, np.ndarray))


class _LearnerActor:
    """One member of a multi-actor learner group (DDP over the host
    collective). Actor-side wrapper around a Learner subclass."""

    def __init__(self, learner_cls_blob: bytes, kwargs_blob: bytes,
                 rank: int, world_size: int, group_name: str):
        from ray_tpu.core import serialization
        from ray_tpu.parallel import collective
        learner_cls = serialization.loads(learner_cls_blob)
        kwargs = serialization.loads(kwargs_blob)
        collective.init_collective_group(world_size, rank, group_name)
        self.learner = learner_cls(collective_group=group_name, **kwargs)

    def update(self, batch_blob: bytes) -> Dict[str, Any]:
        import jax
        from ray_tpu.core import serialization
        metrics = self.learner.update(serialization.loads(batch_blob))
        return {k: float(v) for k, v in
                jax.tree.map(np.asarray, metrics).items()}

    def get_weights(self):
        return self.learner.get_weights()

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)

    def ping(self):
        return True


class LearnerGroup:
    """1 local learner, or N learner actors with gradient allreduce.

    Reference: rllib/core/learner/learner_group.py:101 (update_from_batch
    splits the batch across learners; torch DDP allreduces grads).
    """

    def __init__(self, learner_cls: Callable[..., Learner], *,
                 num_learners: int = 0, group_name: str = "rl/learners",
                 **learner_kwargs):
        self.num_learners = num_learners
        if num_learners <= 1:
            self._local = learner_cls(**learner_kwargs)
            self._actors = None
            return
        import ray_tpu
        from ray_tpu.core import serialization
        self._local = None
        cls_blob = serialization.dumps(learner_cls)
        kw_blob = serialization.dumps(learner_kwargs)
        actor_cls = ray_tpu.remote(_LearnerActor)
        self._actors = [
            actor_cls.remote(cls_blob, kw_blob, rank, num_learners,
                             group_name)
            for rank in range(num_learners)]
        ray_tpu.get([a.ping.remote() for a in self._actors])

    def shutdown(self) -> None:
        """Kill learner actors (leaked ones would hold CPUs forever)."""
        if self._actors:
            import ray_tpu
            for actor in self._actors:
                try:
                    ray_tpu.kill(actor)
                except Exception:  # noqa: BLE001 — actor already dead
                    logging.getLogger(__name__).debug(
                        "learner kill failed", exc_info=True)
            self._actors = None

    def update(self, batch) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu
        from ray_tpu.core import serialization
        n = len(self._actors)
        size = len(next(iter(batch.values())))
        # every actor must get >= 1 row (an empty shard would NaN the
        # loss and the allreduce would poison every replica); wrap
        # around when the batch is smaller than the group
        idx = np.arange(max(size, n)) % size
        chunks = np.array_split(idx, n)
        refs = []
        for actor, chunk in zip(self._actors, chunks):
            sub = {k: np.asarray(v)[chunk] for k, v in batch.items()}
            refs.append(actor.update.remote(serialization.dumps(sub)))
        return mean_metrics(ray_tpu.get(refs))

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        import ray_tpu
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state):
        if self._local is not None:
            self._local.set_state(state)
        else:
            import ray_tpu
            ray_tpu.get([a.set_state.remote(state) for a in self._actors])

    @property
    def local_learner(self) -> Optional[Learner]:
        return self._local
