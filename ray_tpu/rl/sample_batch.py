"""SampleBatch: columnar rollout storage (reference:
rllib/policy/sample_batch.py — SampleBatch with OBS/ACTIONS/REWARDS
columns, concat_samples).  Host-side representation is numpy; learners
move columns to device as one transfer.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
TRUNCATEDS = "truncateds"
FINAL_OBS = "final_obs"
LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """dict[str, np.ndarray] with a consistent leading (time/batch) dim."""

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def rows(self) -> int:
        return len(self)

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int,
                    rng: np.random.Generator = None) -> Iterator["SampleBatch"]:
        batch = self.shuffle(rng) if rng is not None else self
        for start in range(0, len(batch) - size + 1, size):
            yield batch.slice(start, start + size)


def concat_samples(batches: Sequence[SampleBatch]) -> SampleBatch:
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch(
        {k: np.concatenate([np.asarray(b[k]) for b in batches]) for k in keys})
