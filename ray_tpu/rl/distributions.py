"""Action distributions (reference: rllib/models/distributions.py and
the torch Categorical/DiagGaussian wrappers in
rllib/models/torch/torch_distributions.py) as pure JAX."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    def __init__(self, logits):
        self.logits = logits - jax.scipy.special.logsumexp(
            logits, axis=-1, keepdims=True)

    def sample(self, key):
        return jax.random.categorical(key, self.logits)

    def log_prob(self, actions):
        return jnp.take_along_axis(
            self.logits, actions[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)

    def entropy(self):
        p = jnp.exp(self.logits)
        return -jnp.sum(p * self.logits, axis=-1)

    def mode(self):
        return jnp.argmax(self.logits, axis=-1)


class DiagGaussian:
    def __init__(self, mean, log_std):
        self.mean = mean
        self.log_std = jnp.broadcast_to(log_std, mean.shape)

    def sample(self, key):
        return self.mean + jnp.exp(self.log_std) * jax.random.normal(
            key, self.mean.shape)

    def log_prob(self, actions):
        var = jnp.exp(2 * self.log_std)
        ll = -0.5 * ((actions - self.mean) ** 2 / var
                     + 2 * self.log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    def entropy(self):
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e),
                       axis=-1)

    def mode(self):
        return self.mean
