"""EnvRunners: vectorized experience collection.

Reference: rllib/env/single_agent_env_runner.py:68 (sample() over
gymnasium vector envs, weights synced from the learner group) and
env_runner_group.py:69 (the actor pool). Two implementations:

- `SingleAgentEnvRunner`: arbitrary Python `Env`s, numpy stepping with a
  jitted policy forward. Runs in-process or as an actor on CPU nodes.
- `JaxEnvRunner`: `JaxEnv`s only — the whole rollout (policy forward,
  env.step, auto-reset) is ONE jitted `lax.scan`, vmapped over
  `num_envs`. On TPU the sampling loop never leaves the device; there
  is no per-step host round-trip at all.

Both return time-major columns shaped [T, N, ...] plus a bootstrap
value, so the learner's GAE treats them identically.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import Env, JaxEnv
from ray_tpu.rl.rl_module import RLModuleSpec
from ray_tpu.rl.sample_batch import (
    ACTIONS, DONES, FINAL_OBS, LOGP, OBS, REWARDS, TRUNCATEDS, VF_PREDS,
    SampleBatch)


class SingleAgentEnvRunner:
    """Steps `num_envs` Python envs with the current policy."""

    def __init__(self, env_creator: Callable[[], Env],
                 module_spec: RLModuleSpec, *, num_envs: int = 1,
                 rollout_len: int = 128, seed: int = 0,
                 explore: bool = True, connectors=None):
        import jax
        self.envs = [env_creator() for _ in range(num_envs)]
        self.spec = module_spec
        self.rollout_len = rollout_len
        self.explore = explore
        # env→module connector pipeline (ray_tpu/rl/connectors.py);
        # raw env observations pass through it before every policy query
        self.connectors = connectors
        self._key = jax.random.PRNGKey(seed)
        self.params = jax.tree.map(np.asarray,
                                   module_spec.init(jax.random.PRNGKey(seed)))
        self._obs = np.stack(
            [env.reset(seed=seed + i)[0] for i, env in enumerate(self.envs)])
        if self.connectors is not None:
            self._obs = self.connectors.on_obs(self._obs)
        self._ep_return = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, dtype=np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []

        def _act(params, obs, key):
            dist, value = module_spec.forward(params, obs)
            if explore:
                action = dist.sample(key)
            else:
                action = dist.mode()
            return action, dist.log_prob(action), value

        self._act = jax.jit(_act)

    def set_weights(self, params) -> None:
        import jax
        self.params = jax.tree.map(np.asarray, params)

    def get_weights(self):
        return self.params

    def sample(self) -> SampleBatch:
        """One fragment: [T, N] columns + bootstrap_value [N]."""
        import jax
        T, N = self.rollout_len, len(self.envs)
        cols: Dict[str, list] = {k: [] for k in
                                 (OBS, ACTIONS, LOGP, VF_PREDS, REWARDS,
                                  DONES, TRUNCATEDS, FINAL_OBS)}
        for _ in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value = self._act(self.params, self._obs, sub)
            action = np.asarray(action)
            cols[OBS].append(self._obs.copy())
            cols[ACTIONS].append(action)
            cols[LOGP].append(np.asarray(logp))
            cols[VF_PREDS].append(np.asarray(value))
            rewards = np.zeros(N, dtype=np.float32)
            dones = np.zeros(N, dtype=bool)
            truncateds = np.zeros(N, dtype=bool)
            final_obs = None
            raw_next = None
            for i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(action[i])
                if final_obs is None:
                    # raw env shape — with connectors (e.g. FrameStack)
                    # it differs from the transformed self._obs shape
                    final_obs = np.zeros((N, *np.shape(obs)),
                                         dtype=np.asarray(obs).dtype)
                    raw_next = np.zeros_like(final_obs)
                rewards[i] = rew
                final_obs[i] = obs  # the true next obs, pre-reset
                self._ep_return[i] += rew
                self._ep_len[i] += 1
                if term or trunc:
                    dones[i] = True
                    truncateds[i] = trunc and not term
                    self._completed.append(float(self._ep_return[i]))
                    self._completed_lens.append(int(self._ep_len[i]))
                    self._ep_return[i] = 0.0
                    self._ep_len[i] = 0
                    obs, _ = env.reset()
                raw_next[i] = obs
            if self.connectors is not None:
                # dones marks envs that just reset: stateful connectors
                # (FrameStack) must not leak the dead episode's frames
                self._obs = self.connectors.on_obs(raw_next, resets=dones)
            else:
                self._obs = raw_next
            cols[REWARDS].append(rewards)
            cols[DONES].append(dones)
            cols[TRUNCATEDS].append(truncateds)
            cols[FINAL_OBS].append(final_obs)
        batch = SampleBatch({k: np.stack(v) for k, v in cols.items()})
        if self.connectors is not None:
            batch = self.connectors.on_batch(batch)
        bootstrap = np.asarray(
            self.spec.compute_values(self.params, self._obs))
        batch["bootstrap_value"] = bootstrap
        return batch

    def reset_envs(self) -> None:
        """Fresh episodes + cleared accumulators — evaluation reuses a
        cached runner across calls, and episodes begun under the
        previous weights must not contaminate the new measurement."""
        self._obs = np.stack([env.reset()[0] for env in self.envs])
        if self.connectors is not None:
            self._obs = self.connectors.on_obs(
                self._obs, resets=np.ones(len(self.envs), bool))
        self._ep_return[:] = 0.0
        self._ep_len[:] = 0
        self._completed = []
        self._completed_lens = []

    def pop_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_returns": self._completed,
            "episode_lens": self._completed_lens,
        }
        self._completed = []
        self._completed_lens = []
        return out

    def get_connector_state(self):
        return (self.connectors.get_state()
                if self.connectors is not None else {})

    def pop_connector_delta(self):
        return (self.connectors.pop_delta_state()
                if self.connectors is not None else {})

    def set_connector_state(self, state) -> None:
        if self.connectors is not None:
            self.connectors.set_state(state)

    def ping(self) -> bool:
        return True


class JaxEnvRunner:
    """Fully-jitted rollouts over a `JaxEnv` (PureJaxRL-style scan)."""

    def __init__(self, env: JaxEnv, module_spec: RLModuleSpec, *,
                 num_envs: int = 8, rollout_len: int = 128, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.env = env
        self.spec = module_spec
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self._key = jax.random.PRNGKey(seed)
        self._key, init_key = jax.random.split(self._key)
        keys = jax.random.split(init_key, num_envs)
        self._env_state, self._obs = jax.vmap(env.reset)(keys)
        self._ep_return = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, dtype=np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []

        def rollout(params, env_state, obs, key):
            def step_fn(carry, _):
                env_state, obs, key = carry
                key, k_act, k_env = jax.random.split(key, 3)
                dist, value = module_spec.forward(params, obs)
                action = dist.sample(k_act)
                logp = dist.log_prob(action)
                env_keys = jax.random.split(k_env, num_envs)
                env_state, step_out = jax.vmap(env.step)(
                    env_state, action, env_keys)
                next_obs = step_out["obs"]
                done = step_out["terminated"] | step_out["truncated"]
                out = {OBS: obs, ACTIONS: action, LOGP: logp,
                       VF_PREDS: value,
                       REWARDS: jnp.asarray(step_out["reward"],
                                            jnp.float32),
                       DONES: done,
                       TRUNCATEDS: step_out["truncated"],
                       FINAL_OBS: step_out["final_obs"]}
                return (env_state, next_obs, key), out

            (env_state, obs, key), cols = jax.lax.scan(
                step_fn, (env_state, obs, key), None, length=rollout_len)
            bootstrap = module_spec.compute_values(params, obs)
            cols["bootstrap_value"] = bootstrap
            return env_state, obs, cols

        self._rollout = jax.jit(rollout)

    def sample_device(self, params):
        """Rollout with columns left on device ([T, N] jax arrays)."""
        import jax
        self._key, sub = jax.random.split(self._key)
        self._env_state, self._obs, cols = self._rollout(
            params, self._env_state, self._obs, sub)
        self._track_episodes(np.asarray(cols[REWARDS]),
                             np.asarray(cols[DONES]))
        return cols

    def _track_episodes(self, rewards: np.ndarray, dones: np.ndarray):
        T, N = rewards.shape
        for t in range(T):
            self._ep_return += rewards[t]
            self._ep_len += 1
            for i in np.nonzero(dones[t])[0]:
                self._completed.append(float(self._ep_return[i]))
                self._completed_lens.append(int(self._ep_len[i]))
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0

    def pop_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_returns": self._completed,
            "episode_lens": self._completed_lens,
        }
        self._completed = []
        self._completed_lens = []
        return out
