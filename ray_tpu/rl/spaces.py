"""Observation/action spaces (gymnasium-compatible surface).

The reference consumes gymnasium spaces throughout RLlib
(reference: rllib/core/rl_module/rl_module.py:256 takes
observation_space/action_space). gymnasium is not a dependency here;
these two cover the single-agent algorithms in-tree.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


class Space:
    shape: Tuple[int, ...]
    dtype: Any

    def sample(self, rng: Optional[np.random.Generator] = None):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Discrete(Space):
    """{0, 1, ..., n-1}."""

    def __init__(self, n: int):
        if n <= 0:
            raise ValueError(f"Discrete space needs n > 0, got {n}")
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int32

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))

    def contains(self, x) -> bool:
        try:
            i = int(x)
        except (TypeError, ValueError):
            return False
        return 0 <= i < self.n

    def __repr__(self):
        return f"Discrete({self.n})"

    def __eq__(self, other):
        return isinstance(other, Discrete) and other.n == self.n

    def __hash__(self):
        return hash(("Discrete", self.n))


class Box(Space):
    """Bounded (possibly unbounded) box in R^shape."""

    def __init__(self, low, high, shape: Optional[Tuple[int, ...]] = None,
                 dtype=np.float32):
        if shape is None:
            shape = np.broadcast(np.asarray(low), np.asarray(high)).shape
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.low = np.broadcast_to(np.asarray(low, dtype), self.shape).copy()
        self.high = np.broadcast_to(np.asarray(high, dtype), self.shape).copy()

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        low = np.where(np.isfinite(self.low), self.low, -1.0)
        high = np.where(np.isfinite(self.high), self.high, 1.0)
        return rng.uniform(low, high, self.shape).astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return (x.shape == self.shape and np.all(x >= self.low - 1e-6)
                and np.all(x <= self.high + 1e-6))

    def __repr__(self):
        return f"Box(shape={self.shape}, dtype={self.dtype})"

    def __eq__(self, other):
        # exact comparison, consistent with __hash__
        return (isinstance(other, Box) and other.shape == self.shape
                and other.dtype == self.dtype
                and np.array_equal(other.low, self.low)
                and np.array_equal(other.high, self.high))

    def __hash__(self):
        return hash(("Box", self.shape, str(self.dtype),
                     self.low.tobytes(), self.high.tobytes()))


def flat_dim(space: Space) -> int:
    """Input width of a dense network reading this space."""
    if isinstance(space, Discrete):
        return space.n
    return int(np.prod(space.shape)) if space.shape else 1
