from ray_tpu.rl.algorithms.appo import APPO, APPOConfig
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.algorithms.sac import SAC, SACConfig
from ray_tpu.rl.algorithms.bc import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig
from ray_tpu.rl.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rl.algorithms.iql import IQL, IQLConfig

__all__ = ["APPO", "APPOConfig", "PPO", "PPOConfig", "DQN", "DQNConfig",
           "SAC", "SACConfig", "BC", "BCConfig", "MARWIL", "MARWILConfig",
           "CQL", "CQLConfig", "IQL", "IQLConfig", "DreamerV3",
           "DreamerV3Config"]
