from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig"]
