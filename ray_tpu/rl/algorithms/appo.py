"""APPO — asynchronous PPO.

Reference: rllib/algorithms/appo/ — PPO's clipped surrogate applied
asynchronously: env-runner actors sample continuously and the learner
consumes whichever fragment arrives next, so slow runners never stall
the update loop (decoupled sampling/learning, the IMPALA architecture
with PPO's loss). Staleness is bounded by the ratio clip: the surrogate
is computed against the BEHAVIOR policy's log-probs recorded at sample
time, exactly PPO's importance-sampling form, so a fragment collected a
few weight versions ago contributes a clipped, conservative update.

Weights are pushed to runners fire-and-forget after every update; each
runner's next fragment uses whatever version it last received.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig


class APPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        # APPO defaults: single pass per fragment (stale data does not
        # reward many epochs), more runners than PPO
        self.num_epochs = 1
        self.num_env_runners = 2
        # max fragments consumed per training_step() call
        self.max_fragments_per_step = 4


class APPO(PPO):
    # async runner-group path has no multi-agent support yet
    supports_multi_agent = False

    def setup(self, config: APPOConfig) -> None:
        if config.num_env_runners < 1:
            raise ValueError("APPO requires num_env_runners >= 1 "
                             "(asynchronous sampling needs actors)")
        super().setup(config)
        assert self._remote, "APPO runner group must be remote actors"
        # ref -> runner index, for resubmission on completion
        self._inflight: Dict[Any, int] = {}
        self._runner_failures: Dict[int, int] = {}

    def _launch(self, idx: int) -> None:
        ref = self.runners[idx].sample.remote()
        self._inflight[ref] = idx

    # -- fragment hooks (IMPALA overrides both: V-trace consumes the
    #    fragments time-major, without GAE or shuffled SGD epochs) -----
    def _prepare_fragment(self, cols, weights):
        return self._postprocess(cols, weights)

    def _train_fragments(self, batches) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import concat_samples
        batch = concat_samples(batches)
        self._env_steps_lifetime += len(batch)
        return self._sgd_epochs(batch)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        from ray_tpu.core import serialization

        cfg = self.config
        weights = self.learner_group.get_weights()
        if not self._inflight:
            for idx, runner in enumerate(self.runners):
                # fire-and-forget weight push: the completed result is
                # reclaimed by the owner after the borrow grace window
                runner.set_weights.remote(weights)  # graftlint: disable=GL015
                self._launch(idx)

        batches = []
        deltas = []
        consumed = 0
        failures = 0
        pushed = set()  # weights are fixed within a step: push once
        metrics: Dict[str, Any] = {}
        while consumed < cfg.max_fragments_per_step:
            if failures > 3 * max(1, len(self.runners)):
                raise RuntimeError(
                    "APPO: every env runner is failing repeatedly; "
                    "giving up this step (check runner logs)")
            ready, _ = ray_tpu.wait(list(self._inflight),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break  # stall: surface it via the metrics below
            ref = ready[0]
            idx = self._inflight.pop(ref)
            payload = None
            try:
                payload = serialization.loads(ray_tpu.get(ref))
                self._runner_failures[idx] = 0
            except Exception as exc:  # noqa: BLE001 — a failing runner
                # must not silently leave the rotation NOR busy-spin:
                # after repeated failures, recreate the actor from its
                # construction blob (a dead actor fails new tasks
                # instantly, which would otherwise livelock this loop)
                failures += 1
                count = self._runner_failures.get(idx, 0) + 1
                self._runner_failures[idx] = count
                if count >= 2:
                    print(f"APPO: recreating env runner {idx} after "
                          f"{count} failures ({exc!r})")
                    try:
                        # the old actor may be alive (application
                        # errors don't kill the process) — leaking it
                        # would pin its CPU forever
                        ray_tpu.kill(self.runners[idx])
                    except Exception:  # noqa: BLE001 — already dead
                        import logging
                        logging.getLogger(__name__).debug(
                            "runner kill failed", exc_info=True)
                    self.runners[idx] = self._runner_actor_cls.remote(
                        self._runner_blobs[idx])
                    self._runner_failures[idx] = 0
                    pushed.discard(idx)  # the fresh actor has
                    # construction-time weights; it MUST get a push
            # resume sampling IMMEDIATELY; weights go once per runner
            # per step (they only change after the sgd below)
            if idx not in pushed:
                # fire-and-forget re-push (same contract as the initial
                # launch push above: completed results are reclaimed by
                # the owner after the borrow grace window)
                self.runners[idx].set_weights.remote(weights)  # graftlint: disable=GL015
                pushed.add(idx)
            self._launch(idx)
            if payload is None:
                continue
            # driver-side processing stays OUTSIDE the runner-failure
            # handler: a postprocess bug must surface as itself, not
            # kill healthy actors as misattributed "runner failures"
            cols, runner_metrics, delta = payload
            self.record_episodes(runner_metrics["episode_returns"])
            batches.append(self._prepare_fragment(cols, weights))
            deltas.append(delta)
            consumed += 1
        if batches:
            metrics = self._train_fragments(batches)
        if (self._connector_template is not None and deltas):
            # deltas arrived WITH the sample payloads (no extra round
            # trip — a gather here would barrier on in-flight samples)
            self._connector_state = (
                self._connector_template.merge_states(
                    [self._connector_state] + deltas))
            for r in self.runners:  # fire-and-forget broadcast (the
                # completed result is reclaimed after the grace window)
                r.set_connector_state.remote(self._connector_state)  # graftlint: disable=GL015
        metrics["fragments_consumed"] = consumed
        metrics["fragments_in_flight"] = len(self._inflight)
        return metrics

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()


APPOConfig.algo_class = APPO
