"""IMPALA — importance-weighted actor-learner architecture.

Reference: rllib/algorithms/impala/ — decoupled actors sample with a
stale behavior policy while the learner trains continuously; V-trace
(Espeholt et al. 2018) corrects the off-policyness with truncated
importance weights, giving n-step value targets that contract to the
target policy's value function. The actor/learner plumbing is shared
with APPO (same async fragment loop); only the loss and the batch
layout differ: V-trace's recursion needs TIME-MAJOR [T, N] fragments,
so IMPALA trains one pass per fragment without GAE or shuffled
minibatch epochs. The whole V-trace computation jits — on TPU the
scan lowers to one fused XLA while-loop.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithms.appo import APPO, APPOConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.sample_batch import (
    ACTIONS, DONES, FINAL_OBS, LOGP, OBS, REWARDS, TRUNCATEDS)


def vtrace_returns(log_rhos, discounts, rewards, values, bootstrap_value,
                   *, clip_rho_threshold: float = 1.0,
                   clip_pg_rho_threshold: float = 1.0):
    """V-trace targets ``vs`` and policy-gradient advantages over
    time-major [T, N] columns (reference:
    rllib/algorithms/impala/vtrace; Espeholt et al. 2018 eq. 1).
    jit/grad-safe — callers stop_gradient as needed."""
    import jax
    import jax.numpy as jnp

    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    cs = jnp.minimum(1.0, rhos)
    next_values = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * next_values - values)

    def scan_fn(acc, inp):
        delta, discount, c = inp
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    clipped_pg_rhos = jnp.minimum(clip_pg_rho_threshold, rhos)
    pg_advantages = clipped_pg_rhos * (
        rewards + discounts * next_vs - values)
    return vs, pg_advantages


class IMPALAConfig(APPOConfig):
    def __init__(self):
        super().__init__()
        self.clip_rho_threshold = 1.0
        self.clip_pg_rho_threshold = 1.0
        self.lr = 5e-4
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5


class IMPALALearner(Learner):
    def __init__(self, module_spec, *, gamma: float = 0.99,
                 clip_rho_threshold: float = 1.0,
                 clip_pg_rho_threshold: float = 1.0,
                 vf_loss_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, **kwargs):
        self.gamma = gamma
        self.clip_rho_threshold = clip_rho_threshold
        self.clip_pg_rho_threshold = clip_pg_rho_threshold
        self.vf_loss_coeff = vf_loss_coeff
        self.entropy_coeff = entropy_coeff
        super().__init__(module_spec, **kwargs)

    def loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        dist, values = self.spec.forward(params, batch[OBS])  # [T, N]
        logp = dist.log_prob(batch[ACTIONS])
        log_rhos = logp - batch[LOGP]  # current vs behavior policy
        dones = jnp.asarray(batch[DONES], jnp.float32)
        # truncated episodes bootstrap from the true next obs (time
        # limits are not terminations)
        v_final = jax.lax.stop_gradient(
            self.spec.compute_values(params, batch[FINAL_OBS]))
        rewards = (jnp.asarray(batch[REWARDS], jnp.float32)
                   + self.gamma * v_final
                   * jnp.asarray(batch[TRUNCATEDS], jnp.float32))
        discounts = self.gamma * (1.0 - dones)
        # Bootstrap with the LEARNER's value of the fragment's true
        # next obs (v_final[-1] — FINAL_OBS is pre-reset): the
        # runner-shipped bootstrap_value came from the stale behavior
        # weights and would mix two value functions at every fragment
        # tail (reference: vtrace computes bootstrap learner-side).
        vs, pg_adv = vtrace_returns(
            jax.lax.stop_gradient(log_rhos), discounts, rewards,
            jax.lax.stop_gradient(values), v_final[-1],
            clip_rho_threshold=self.clip_rho_threshold,
            clip_pg_rho_threshold=self.clip_pg_rho_threshold)
        policy_loss = -(logp * pg_adv).mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = dist.entropy().mean()
        total = (policy_loss + self.vf_loss_coeff * vf_loss
                 - self.entropy_coeff * entropy)
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.exp(log_rhos).mean(),
        }


class IMPALA(APPO):
    """APPO's async actor loop + the V-trace learner."""

    learner_cls = IMPALALearner

    def _learner_kwargs(self, config) -> Dict[str, Any]:
        return dict(
            module_spec=self.spec, lr=config.lr,
            grad_clip=config.grad_clip, seed=config.seed,
            gamma=config.gamma,
            clip_rho_threshold=config.clip_rho_threshold,
            clip_pg_rho_threshold=config.clip_pg_rho_threshold,
            vf_loss_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff)

    def setup(self, config: IMPALAConfig) -> None:
        if config.num_learners > 1:
            # V-trace consumes whole time-major sequences; splitting a
            # fragment's rows across learner actors would cut them
            raise ValueError("IMPALA supports num_learners <= 1 "
                             "(fragments train whole, time-major)")
        super().setup(config)

    # -- fragment hooks: keep time-major, no GAE/epochs ---------------
    def _prepare_fragment(self, cols, weights):
        return {key: np.asarray(value) for key, value in cols.items()}

    def _train_fragments(self, batches: List[dict]) -> Dict[str, Any]:
        from ray_tpu.rl.learner import mean_metrics
        learner = self.learner_group.local_learner
        all_metrics = []
        for batch in batches:
            self._env_steps_lifetime += int(batch[REWARDS].size)
            all_metrics.append(learner.update(batch))
        return mean_metrics(all_metrics)


IMPALAConfig.algo_class = IMPALA
