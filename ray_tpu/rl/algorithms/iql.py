"""IQL — Implicit Q-Learning for offline RL (Kostrikov et al. 2021).

Reference: rllib/algorithms/iql/ (iql.py config on MARWIL, torch
learner iql_torch_learner.py — expectile value regression + advantage
weighted actor). Here it rides the in-tree SAC nets plus a state-value
head:

    L_V  = E[ rho_tau( min_i Qtgt_i(s, a) - V(s) ) ]     (expectile)
    L_Q  = E[ ( Q(s, a) - (r + gamma (1-d) V(s')) )^2 ]
    L_pi = -E[ exp(beta (Qtgt - V)) clipped * log pi(a|s) ]   (AWR)

All three train from the fixed dataset; the policy never queries the
env (evaluation rollouts only). The squashed-Gaussian log-prob of DATA
actions uses the atanh inverse with edge clipping.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithms.cql import CQLConfig
from ray_tpu.rl.algorithms.offline_base import (
    OfflineContinuousAlgorithm)
from ray_tpu.rl.rl_module import _dense_forward, _dense_init


class IQLConfig(CQLConfig):
    """Shares CQL's offline/evaluation plumbing; IQL-specific knobs
    mirror the reference's (expectile tau, AWR beta)."""

    def __init__(self):
        super().__init__()
        self.expectile = 0.8
        self.beta = 3.0          # advantage temperature (reference beta)
        self.adv_clip = 100.0    # exp-advantage clip (reference: 100)

    def training(self, *, expectile: Optional[float] = None,
                 beta: Optional[float] = None,
                 adv_clip: Optional[float] = None, **kw) -> "IQLConfig":
        super().training(**kw)
        if expectile is not None:
            self.expectile = float(expectile)
        if beta is not None:
            self.beta = float(beta)
        if adv_clip is not None:
            self.adv_clip = float(adv_clip)
        return self


class IQL(OfflineContinuousAlgorithm):
    _eval_seed_base = 30_000

    def setup(self, config: IQLConfig) -> None:
        import jax
        import jax.numpy as jnp

        nets = self._setup_common(config)
        # state-value head V(s) (reference: iql module's vf branch) —
        # added BEFORE _finish_setup so the optimizer covers it
        self.params["vf"] = _dense_init(
            jax.random.PRNGKey(config.seed + 7),
            [self.obs_dim, *config.hidden, 1])
        self._finish_setup(config)
        scale, center = nets.scale, nets.center

        gamma, tau = config.gamma, config.tau
        expectile = config.expectile
        beta = config.beta
        adv_clip = config.adv_clip

        def v_of(p, obs):
            return _dense_forward(p["vf"], obs).squeeze(-1)

        def logp_data(p, obs, act):
            """log pi(a_data|s) for the squashed Gaussian via atanh
            inverse (edge-clipped; reference: torch TanhNormal)."""
            out = _dense_forward(p["pi"], obs)
            mean, log_std = jnp.split(out, 2, axis=-1)
            from ray_tpu.rl.algorithms.sac import (_LOG_STD_MAX,
                                                   _LOG_STD_MIN)
            log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
            std = jnp.exp(log_std)
            a = jnp.clip((act - center) / scale, -1.0 + 1e-6,
                         1.0 - 1e-6)
            u = jnp.arctanh(a)
            logp_u = jnp.sum(
                -0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                        + jnp.log(2 * jnp.pi)), axis=-1)
            correction = jnp.sum(
                2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)),
                axis=-1)
            return logp_u - correction

        def train_step(params, target_params, opt_state, batch):
            q_tgt = jnp.minimum(
                nets.q(target_params, "q1", batch["obs"],
                       batch["actions"]),
                nets.q(target_params, "q2", batch["obs"],
                       batch["actions"]))

            def loss_fn(p):
                # expectile value regression toward target-Q
                v = v_of(p, batch["obs"])
                diff = q_tgt - v
                weight = jnp.where(diff > 0, expectile, 1 - expectile)
                v_loss = jnp.mean(weight * diff ** 2)
                # TD critics toward r + gamma V(s')
                v_next = jax.lax.stop_gradient(
                    v_of(p, batch["next_obs"]))
                y = (batch["rewards"]
                     + gamma * (1.0 - batch["dones"]) * v_next)
                q1 = nets.q(p, "q1", batch["obs"], batch["actions"])
                q2 = nets.q(p, "q2", batch["obs"], batch["actions"])
                q_loss = (jnp.mean((q1 - y) ** 2)
                          + jnp.mean((q2 - y) ** 2))
                # advantage-weighted regression actor
                adv = q_tgt - jax.lax.stop_gradient(v)
                w = jnp.minimum(jnp.exp(beta * adv), adv_clip)
                logp = logp_data(p, batch["obs"], batch["actions"])
                pi_loss = -jnp.mean(jax.lax.stop_gradient(w) * logp)
                total = v_loss + q_loss + pi_loss
                return total, (v_loss, q_loss, pi_loss)

            (_, (v_l, q_l, pi_l)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state,
                                                 params)
            params = self._optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, p_: (1.0 - tau) * t + tau * p_,
                target_params, params)
            return params, target_params, opt_state, v_l, q_l, pi_l

        self._train_step = jax.jit(train_step)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        v_l = q_l = pi_l = float("nan")
        for _ in range(cfg.num_gradient_steps):
            batch = self.data.sample(cfg.train_batch_size, self._rng)
            (self.params, self.target_params, self.opt_state, v_l, q_l,
             pi_l) = self._train_step(
                self.params, self.target_params, self.opt_state,
                dict(batch))
            self._updates += 1
        if cfg.evaluation_episodes:
            self.record_episodes(
                self._evaluate(cfg.evaluation_episodes))
        return {
            "value_loss": float(v_l),
            "critic_loss": float(q_l),
            "actor_loss": float(pi_l),
            "num_updates": self._updates,
        }


IQLConfig.algo_class = IQL
