"""DQN (reference: rllib/algorithms/dqn/dqn.py — replay buffer +
target network; loss in dqn_rainbow_torch_learner.py). Double-DQN
target, epsilon-greedy collection, numpy circular replay buffer."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.rl_module import _dense_forward, _dense_init
from ray_tpu.rl.spaces import Discrete


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.target_update_freq = 500
        self.train_batch_size = 64
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.num_gradient_steps = 32
        self.num_envs_per_env_runner = 4
        self.rollout_fragment_length = 64


class ReplayBuffer:
    """Circular uniform replay (reference:
    rllib/utils/replay_buffers/replay_buffer.py)."""

    def __init__(self, capacity: int, obs_shape, obs_dtype=np.float32):
        self.capacity = capacity
        self.obs = np.zeros((capacity, *obs_shape), dtype=obs_dtype)
        self.next_obs = np.zeros_like(self.obs)
        self.actions = np.zeros(capacity, dtype=np.int32)
        self.rewards = np.zeros(capacity, dtype=np.float32)
        self.dones = np.zeros(capacity, dtype=np.float32)
        self.pos = 0
        self.size = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones):
        for i in range(len(obs)):
            p = self.pos
            self.obs[p] = obs[i]
            self.actions[p] = actions[i]
            self.rewards[p] = rewards[i]
            self.next_obs[p] = next_obs[i]
            self.dones[p] = dones[i]
            self.pos = (p + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int, rng: np.random.Generator):
        idx = rng.integers(self.size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
        }


class DQN(Algorithm):
    def setup(self, config: DQNConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        env0 = config.make_python_env()
        if not isinstance(env0.action_space, Discrete):
            raise ValueError("DQN needs a Discrete action space")
        self.envs = [config.make_python_env()
                     for _ in range(config.num_envs_per_env_runner)]
        from ray_tpu.rl.spaces import flat_dim
        self.n_actions = env0.action_space.n
        obs_shape = env0.observation_space.shape
        obs_dim = flat_dim(env0.observation_space)
        self._rng = np.random.default_rng(config.seed)
        self.buffer = ReplayBuffer(config.buffer_size, obs_shape)

        key = jax.random.PRNGKey(config.seed)
        dims = [obs_dim, *config.hidden, self.n_actions]
        self.params = _dense_init(key, dims)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._grad_updates = 0

        def q_values(params, obs):
            return _dense_forward(params, obs)

        def train_step(params, target_params, opt_state, batch):
            gamma = config.gamma

            def loss_fn(p):
                q = q_values(p, batch["obs"])
                q_taken = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32),
                    axis=-1).squeeze(-1)
                # double DQN: online net picks, target net evaluates
                next_online = q_values(p, batch["next_obs"])
                next_act = jnp.argmax(next_online, axis=-1)
                next_target = q_values(target_params, batch["next_obs"])
                next_q = jnp.take_along_axis(
                    next_target, next_act[:, None], axis=-1).squeeze(-1)
                target = (batch["rewards"]
                          + gamma * (1.0 - batch["dones"])
                          * jax.lax.stop_gradient(next_q))
                return optax.huber_loss(q_taken, target).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._q_values = jax.jit(q_values)
        self._train_step = jax.jit(train_step)
        self._obs = np.stack(
            [env.reset(seed=config.seed + i)[0]
             for i, env in enumerate(self.envs)])
        self._ep_return = np.zeros(len(self.envs))

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_lifetime
                   / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end
                                           - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        N = len(self.envs)
        for _ in range(cfg.rollout_fragment_length):
            eps = self._epsilon()
            q = np.asarray(self._q_values(self.params, self._obs))
            actions = np.argmax(q, axis=-1)
            explore = self._rng.random(N) < eps
            actions[explore] = self._rng.integers(self.n_actions,
                                                  size=explore.sum())
            next_obs = np.empty_like(self._obs)
            rewards = np.zeros(N, dtype=np.float32)
            dones = np.zeros(N, dtype=np.float32)
            step_obs = np.empty_like(self._obs)
            for i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(int(actions[i]))
                rewards[i] = rew
                next_obs[i] = obs  # true next obs, pre-reset
                self._ep_return[i] += rew
                # terminated cuts the bootstrap; truncation does not
                dones[i] = float(term)
                if term or trunc:
                    self.record_episodes([float(self._ep_return[i])])
                    self._ep_return[i] = 0.0
                    obs, _ = env.reset()
                step_obs[i] = obs
            self.buffer.add_batch(self._obs, actions, rewards, next_obs,
                                  dones)
            self._obs = step_obs
            self._env_steps_lifetime += N

        losses = []
        if self.buffer.size >= cfg.learning_starts:
            import jax
            import jax.numpy as jnp
            for _ in range(cfg.num_gradient_steps):
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                self.params, self.opt_state, loss = self._train_step(
                    self.params, self.target_params, self.opt_state, batch)
                self._grad_updates += 1
                losses.append(float(loss))
                if self._grad_updates % cfg.target_update_freq == 0:
                    self.target_params = jax.tree.map(jnp.copy, self.params)
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "buffer_size": self.buffer.size,
        }


    def evaluate(self) -> Dict[str, Any]:
        """Greedy (argmax-Q) episodes on a dedicated env (reference:
        algorithm.py:1407 evaluate with exploration off)."""
        from ray_tpu.rl.evaluation import evaluate_policy

        def act(obs):
            q = np.asarray(self._q_values(self.params,
                                          np.asarray(obs)[None]))
            return int(np.argmax(q[0]))

        return evaluate_policy(
            self.config.make_python_env, act,
            num_episodes=self.config.evaluation_duration)

    def get_state(self) -> Dict[str, Any]:
        import jax
        state = super().get_state()
        state["params"] = jax.tree.map(np.asarray, self.params)
        state["target_params"] = jax.tree.map(np.asarray,
                                              self.target_params)
        state["opt_state"] = jax.tree.map(np.asarray, self.opt_state)
        state["grad_updates"] = self._grad_updates
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        import jax
        super().set_state(state)
        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.params = as_jnp(state["params"])
        self.target_params = as_jnp(state["target_params"])
        self.opt_state = as_jnp(state["opt_state"])
        self._grad_updates = state["grad_updates"]


DQNConfig.algo_class = DQN
