"""BC and MARWIL — offline RL.

Reference: rllib/algorithms/bc/ (behavior cloning = pure imitation,
-log π(a|s)) and rllib/algorithms/marwil/ (advantage-weighted
imitation: exp(β·Â) weights on the log-likelihood plus a value-head
regression; BC is exactly MARWIL with β = 0 — the reference implements
it that way, and so does this module).

Offline training consumes an ``OfflineData`` store (ray_tpu/rl/
offline.py); per training_step the learner takes ``num_gradient_steps``
jitted updates on sampled minibatches. Evaluation (episode returns in
train results) rolls the greedy policy in the configured env.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.offline import RETURNS, OfflineData
from ray_tpu.rl.sample_batch import ACTIONS, OBS, SampleBatch


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0              # advantage-weighting temperature
        self.vf_coeff = 1.0
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_gradient_steps = 32
        self.offline_data: Optional[OfflineData] = None
        self.evaluation_episodes = 2

    def offline(self, data: OfflineData) -> "MARWILConfig":
        self.offline_data = data
        return self


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.beta = 0.0  # BC = MARWIL with no advantage weighting


class MARWILLearner(Learner):
    def __init__(self, module_spec, *, beta: float = 1.0,
                 vf_coeff: float = 1.0, **kwargs):
        self.beta = beta
        self.vf_coeff = vf_coeff
        super().__init__(module_spec, **kwargs)

    def loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        dist, values = self.spec.forward(params, batch[OBS])
        logp = dist.log_prob(batch[ACTIONS])
        if self.beta == 0.0:
            policy_loss = -jnp.mean(logp)
            vf_loss = jnp.zeros(())
        else:
            adv = batch[RETURNS] - values
            # moving normalization collapses to per-batch normalization
            # here (the reference keeps an EMA of adv²; per-batch is the
            # deterministic equivalent for full-batch offline training)
            adv_n = adv / (jnp.sqrt(jnp.mean(adv ** 2)) + 1e-8)
            weights = jnp.exp(
                jnp.clip(self.beta * jax.lax.stop_gradient(adv_n),
                         -10.0, 10.0))
            policy_loss = -jnp.mean(weights * logp)
            vf_loss = jnp.mean(adv ** 2)
        total = policy_loss + self.vf_coeff * vf_loss
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_logp": jnp.mean(logp)}


class MARWIL(Algorithm):
    def setup(self, config: MARWILConfig) -> None:
        if config.offline_data is None:
            raise ValueError(
                "MARWIL/BC require offline data: "
                "config.offline(OfflineData(episodes))")
        self.spec = config.module_spec()
        self.learner = MARWILLearner(
            self.spec, beta=config.beta, vf_coeff=config.vf_coeff,
            lr=config.lr, grad_clip=config.grad_clip, seed=config.seed)
        self.data = config.offline_data
        self._rng = np.random.default_rng(config.seed)
        # eval artifacts hoisted out of the loop: a fresh lambda per
        # training_step would retrace/recompile every iteration
        self._eval_env = None
        if config.env is not None or config.env_creator is not None:
            import jax
            self._eval_env = config.make_python_env()
            self._eval_act = jax.jit(
                lambda p, o: self.spec.forward(p, o)[0].mode())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.num_gradient_steps):
            batch = self.data.sample(cfg.train_batch_size, self._rng)
            metrics = self.learner.update(batch)
        if cfg.evaluation_episodes and self._eval_env is not None:
            self.record_episodes(self._evaluate(cfg.evaluation_episodes))
        return metrics

    def _evaluate(self, episodes: int):
        env, act = self._eval_env, self._eval_act
        returns = []
        for e in range(episodes):
            obs, _ = env.reset(seed=10_000 + self.iteration * 100 + e)
            total, done = 0.0, False
            for _ in range(1000):
                action = np.asarray(act(self.learner.params, obs[None]))[0]
                if not self.spec.is_continuous:
                    action = int(action)
                obs, rew, term, trunc, _ = env.step(action)
                total += rew
                self._env_steps_lifetime += 1
                if term or trunc:
                    break
            returns.append(total)
        return returns

    def compute_single_action(self, obs: np.ndarray):
        import jax
        dist, _ = self.spec.forward(self.learner.params, obs[None])
        action = np.asarray(dist.mode())[0]
        return int(action) if not self.spec.is_continuous else action

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learner"] = self.learner.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.learner.set_state(state["learner"])


class BC(MARWIL):
    pass


MARWILConfig.algo_class = MARWIL
BCConfig.algo_class = BC
