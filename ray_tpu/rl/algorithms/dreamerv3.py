"""DreamerV3 — model-based RL via a learned world model (Hafner et al.
2023, "Mastering Diverse Domains through World Models").

Reference: rllib/algorithms/dreamerv3/ (dreamerv3.py config,
torch/models/{world_model,actor_network,critic_network}.py). This is a
compact JAX expression of the same architecture for vector
observations + discrete actions:

- RSSM world model: GRU deterministic state + categorical stochastic
  latents (straight-through gradients, 1% unimix), posterior from
  [h, embed(obs)], prior from h; decoder/reward heads regress SYMLOG
  targets, a continue head predicts episode continuation; KL with
  free bits, split dyn/rep with the reference's 1.0/0.1 weights.
- Actor-critic trained purely in IMAGINATION: H-step rollouts from
  posterior states, lambda-returns over predicted rewards/continues,
  critic regresses symlog returns against an EMA slow critic, actor
  uses REINFORCE with percentile-normalized returns + entropy bonus
  ([1] eq. 11-12).

Divergences (stated): MSE-on-symlog replaces the reference's two-hot
distributional heads, and the net sizes default far below "XS" so the
smoke test trains on CPU.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.rl_module import _dense_forward, _dense_init
from ray_tpu.rl.spaces import Discrete


def symlog(x):
    import jax.numpy as jnp
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # reference knob names (dreamerv3.py:101-122), tiny defaults
        self.batch_size_B = 16
        self.batch_length_T = 16
        self.horizon_H = 10
        self.gae_lambda = 0.95
        self.entropy_scale = 3e-4
        self.return_normalization_decay = 0.99
        self.training_ratio = 256       # replayed steps per env step
        self.world_model_lr = 4e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.buffer_capacity = 100_000
        self.deter_size = 64
        self.stoch_classes = 8          # K classes per categorical
        self.stoch_groups = 8           # L categoricals
        self.units = 64                 # MLP width
        self.free_bits = 1.0
        self.unimix = 0.01
        self.critic_ema_decay = 0.98
        self.learning_starts = 1_000

    def training(self, **kw) -> "DreamerV3Config":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self


class _SeqReplay:
    """Uniform sequence replay over a flat transition ring (reference:
    EpisodeReplayBuffer sampling B x T contiguous slices).

    Row convention (the standard Dreamer pairing): a row holds an
    OBSERVATION plus the action that LED to it, the reward received
    WITH it, whether it starts an episode, and whether it is terminal —
    so the RSSM recurrence h_t = f(h_{t-1}, a_{t-1}) never conditions
    on an action chosen after seeing obs_t, and terminal observations
    are real rows the continue head can learn from."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.is_first = np.zeros(capacity, bool)
        self.terminal = np.zeros(capacity, bool)
        self.pos = 0
        self.size = 0

    def add(self, obs, action, reward, is_first, terminal) -> None:
        i = self.pos
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.is_first[i] = is_first
        self.terminal[i] = terminal
        self.pos = (i + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, B: int, T: int, rng) -> Dict[str, np.ndarray]:
        # Sample in LOGICAL (temporal) order: logical 0 = oldest row =
        # self.pos once the ring is full. A logically-contiguous slice
        # maps to physically wrapped indices but never stitches the
        # newest data onto the oldest across the write head, and the
        # +1 keeps the newest row reachable.
        starts = rng.integers(0, self.size - T + 1, size=B)
        logical = starts[:, None] + np.arange(T)[None, :]
        base = self.pos if self.size == self.capacity else 0
        idx = (base + logical) % self.capacity
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "is_first": self.is_first[idx].astype(np.float32),
            "terminal": self.terminal[idx].astype(np.float32),
        }


class DreamerV3(Algorithm):
    supports_multi_agent = False

    def setup(self, config: DreamerV3Config) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        env0 = config.make_python_env()
        if not isinstance(env0.action_space, Discrete):
            raise ValueError(
                "this DreamerV3 targets discrete actions (vector obs); "
                "use SAC/PPO for continuous control")
        self.envs = [env0] + [config.make_python_env()
                              for _ in range(
                                  config.num_envs_per_env_runner - 1)]
        obs_dim = int(np.prod(env0.observation_space.shape))
        n_act = env0.action_space.n
        cfg = config
        D, K, L, U = (cfg.deter_size, cfg.stoch_classes,
                      cfg.stoch_groups, cfg.units)
        Z = K * L
        self._dims = (obs_dim, n_act, D, K, L, Z)
        self._rng = np.random.default_rng(cfg.seed)
        self._key = jax.random.PRNGKey(cfg.seed)
        self.buffer = _SeqReplay(cfg.buffer_capacity, obs_dim)

        def init_params(key):
            ks = jax.random.split(key, 12)
            return {
                # world model
                "embed": _dense_init(ks[0], [obs_dim, U, U]),
                "gru_x": _dense_init(ks[1], [Z + n_act, D]),
                "gru_h": _dense_init(ks[2], [D, 3 * D]),
                "gru_i": _dense_init(ks[3], [D, 3 * D]),
                "prior": _dense_init(ks[4], [D, U, Z]),
                "post": _dense_init(ks[5], [D + U, U, Z]),
                "decoder": _dense_init(ks[6], [D + Z, U, obs_dim]),
                "reward": _dense_init(ks[7], [D + Z, U, 1],
                                      final_gain=0.0),
                "cont": _dense_init(ks[8], [D + Z, U, 1]),
                # actor-critic over [h, z]
                "actor": _dense_init(ks[9], [D + Z, U, n_act],
                                     final_gain=0.01),
                "critic": _dense_init(ks[10], [D + Z, U, 1],
                                      final_gain=0.0),
            }

        self.params = init_params(jax.random.PRNGKey(cfg.seed))
        # jax arrays are immutable: sharing the initial critic params
        # with the slow critic is safe (updates replace, never mutate)
        self.slow_critic = {"critic": self.params["critic"]}
        self.wm_opt = optax.chain(
            optax.clip_by_global_norm(1000.0), optax.adam(cfg.world_model_lr))
        def _head_labels(params):
            # label every leaf under "actor"/"critic" with its head
            # name, so each trains at its own learning rate
            return {k: jax.tree.map(lambda _, k=k: k, params[k])
                    for k in params}

        self.ac_opt = optax.chain(
            optax.clip_by_global_norm(100.0),
            optax.multi_transform(
                {"actor": optax.adam(cfg.actor_lr),
                 "critic": optax.adam(cfg.critic_lr)},
                _head_labels))
        wm_keys = ("embed", "gru_x", "gru_h", "gru_i", "prior", "post",
                   "decoder", "reward", "cont")
        self._wm_keys = wm_keys
        self.wm_opt_state = self.wm_opt.init(
            {k: self.params[k] for k in wm_keys})
        self.ac_opt_state = self.ac_opt.init(
            {k: self.params[k] for k in ("actor", "critic")})
        # percentile-based return normalizer state ([1] eq. 11)
        self._ret_scale = jnp.asarray(1.0, jnp.float32)

        unimix = cfg.unimix

        def gru(p, h, x):
            """Minimal GRU cell (reference: world_model.py GRU core)."""
            xin = jnp.tanh(_dense_forward(p["gru_x"], x))
            gates_h = _dense_forward(p["gru_h"], h)
            gates_i = _dense_forward(p["gru_i"], xin)
            hr, hz, hn = jnp.split(gates_h, 3, axis=-1)
            ir, iz, inn = jnp.split(gates_i, 3, axis=-1)
            r = jax.nn.sigmoid(hr + ir)
            z = jax.nn.sigmoid(hz + iz)
            n = jnp.tanh(r * hn + inn)
            return (1.0 - z) * n + z * h

        def latent_logits(raw):
            """[..., Z] -> [..., L, K] log-probs with unimix."""
            logits = raw.reshape(raw.shape[:-1] + (L, K))
            probs = jax.nn.softmax(logits, -1)
            probs = (1.0 - unimix) * probs + unimix / K
            return jnp.log(probs)

        def sample_latent(logp, key):
            """Straight-through one-hot sample, flattened to [..., Z]."""
            idx = jax.random.categorical(key, logp, axis=-1)
            one_hot = jax.nn.one_hot(idx, K)
            probs = jnp.exp(logp)
            st = one_hot + probs - jax.lax.stop_gradient(probs)
            return st.reshape(st.shape[:-2] + (Z,))

        def obs_step(p, h, z_prev, action_1h, obs, key):
            """One posterior step: (h, z) given previous state + obs."""
            h = gru(p, h, jnp.concatenate([z_prev, action_1h], -1))
            embed = _dense_forward(p["embed"], symlog(obs))
            post_lp = latent_logits(_dense_forward(
                p["post"], jnp.concatenate([h, embed], -1)))
            prior_lp = latent_logits(_dense_forward(p["prior"], h))
            z = sample_latent(post_lp, key)
            return h, z, post_lp, prior_lp

        def img_step(p, h, z_prev, action_1h, key):
            h = gru(p, h, jnp.concatenate([z_prev, action_1h], -1))
            prior_lp = latent_logits(_dense_forward(p["prior"], h))
            z = sample_latent(prior_lp, key)
            return h, z

        def kl(lp_a, lp_b):
            """KL(a || b) over the L categoricals, summed."""
            return jnp.sum(jnp.exp(lp_a) * (lp_a - lp_b), axis=(-2, -1))

        free_bits = cfg.free_bits
        B, T = cfg.batch_size_B, cfg.batch_length_T
        gamma, lam = cfg.gamma, cfg.gae_lambda
        H = cfg.horizon_H
        ent_scale = cfg.entropy_scale
        ret_decay = cfg.return_normalization_decay

        def wm_loss(wm_p, batch, key):
            p = wm_p
            a_1h = jax.nn.one_hot(batch["actions"], n_act)

            def step(carry, t):
                h, z, key = carry
                key, sub = jax.random.split(key)
                # is_first resets the recurrent state ([1] appendix)
                mask = (1.0 - batch["is_first"][:, t])[:, None]
                h_in = h * mask
                z_in = z * mask
                a_in = a_1h[:, t] * mask
                h2, z2, post_lp, prior_lp = obs_step(
                    p, h_in, z_in, a_in, batch["obs"][:, t], sub)
                return (h2, z2, key), (h2, z2, post_lp, prior_lp)

            h0 = jnp.zeros((B, D))
            z0 = jnp.zeros((B, Z))
            (_, _, _), (hs, zs, post_lps, prior_lps) = jax.lax.scan(
                step, (h0, z0, key), jnp.arange(T))
            # scan stacks time-major [T, B, ...]
            feat = jnp.concatenate([hs, zs], -1)
            obs_t = jnp.swapaxes(batch["obs"], 0, 1)
            recon = _dense_forward(p["decoder"], feat)
            recon_loss = jnp.mean(
                jnp.sum((recon - symlog(obs_t)) ** 2, -1))
            rew_pred = _dense_forward(p["reward"], feat).squeeze(-1)
            rew_t = jnp.swapaxes(batch["rewards"], 0, 1)
            reward_loss = jnp.mean((rew_pred - symlog(rew_t)) ** 2)
            cont_logit = _dense_forward(p["cont"], feat).squeeze(-1)
            cont_t = 1.0 - jnp.swapaxes(batch["terminal"], 0, 1)
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(cont_logit, cont_t))
            dyn = jnp.maximum(
                kl(jax.lax.stop_gradient(post_lps), prior_lps),
                free_bits).mean()
            rep = jnp.maximum(
                kl(post_lps, jax.lax.stop_gradient(prior_lps)),
                free_bits).mean()
            total = recon_loss + reward_loss + cont_loss \
                + 1.0 * dyn + 0.1 * rep
            return total, (hs, zs, recon_loss, reward_loss, dyn)

        def ac_loss(ac_p, wm_p, slow_c, start_h, start_z, ret_scale,
                    key):
            """Actor-critic on imagined rollouts from posterior states
            (gradients flow ONLY into actor/critic; the world model is
            frozen here — reference: dreamer_model.dream_trajectory)."""
            p = {**wm_p, **ac_p}
            N = start_h.shape[0]

            def step(carry, _):
                h, z, key = carry
                key, k1, k2 = jax.random.split(key, 3)
                feat = jnp.concatenate([h, z], -1)
                logits = _dense_forward(p["actor"], feat)
                a = jax.random.categorical(k1, logits)
                a_1h = jax.nn.one_hot(a, n_act)
                h2, z2 = img_step(p, h, z, a_1h, k2)
                logp_a = jax.nn.log_softmax(logits)[
                    jnp.arange(N), a]
                ent = -jnp.sum(jax.nn.softmax(logits)
                               * jax.nn.log_softmax(logits), -1)
                return (h2, z2, key), (h2, z2, logp_a, ent)

            (_, _, _), (hs, zs, logp_as, ents) = jax.lax.scan(
                step, (start_h, start_z, key), None, length=H)
            # Full state sequence INCLUDING the start: feats[k] = s_k,
            # so a_k (taken at s_k, logp_as[k]) pairs with baseline
            # v(s_k) and with reward r_{k+1} predicted at s_{k+1} —
            # the Dreamer pairing (rewards arrive WITH states).
            start_feat = jnp.concatenate([start_h, start_z], -1)
            feats = jnp.concatenate(
                [start_feat[None], jnp.concatenate([hs, zs], -1)],
                axis=0)                                   # [H+1, N, F]
            rew = symexp(_dense_forward(
                p["reward"], feats[1:]).squeeze(-1))      # r_1..r_H
            cont = jax.nn.sigmoid(_dense_forward(
                p["cont"], feats[1:]).squeeze(-1))        # c_1..c_H
            disc = gamma * cont
            slow_v = symexp(_dense_forward(
                slow_c["critic"], feats).squeeze(-1))     # v(s_0..s_H)

            # lambda-returns R_k for a_k (k = 0..H-1), slow-critic
            # bootstrapped: R_k = r_{k+1} + disc_{k+1} ((1-lam)
            # v(s_{k+1}) + lam R_{k+1})
            def ret_step(nxt, t):
                r = rew[t] + disc[t] * (
                    (1 - lam) * slow_v[t + 1] + lam * nxt)
                return r, r

            _, returns = jax.lax.scan(ret_step, slow_v[-1],
                                      jnp.arange(H), reverse=True)
            returns = jax.lax.stop_gradient(returns)     # [H, N]
            # imagined steps past a predicted termination must not
            # train anything: weight by the survival probability up to
            # each state (reference: cumprod of continues)
            weights = jax.lax.stop_gradient(jnp.concatenate(
                [jnp.ones((1, N)), jnp.cumprod(cont[:-1], 0)], 0))

            critic_pred = _dense_forward(
                p["critic"],
                jax.lax.stop_gradient(feats[:-1])).squeeze(-1)
            critic_loss = jnp.mean(
                weights * (critic_pred - symlog(returns)) ** 2)

            # percentile return normalization ([1] eq. 11)
            lo = jnp.percentile(returns, 5.0)
            hi = jnp.percentile(returns, 95.0)
            new_scale = (ret_decay * ret_scale
                         + (1 - ret_decay) * jnp.maximum(1.0, hi - lo))
            value = symexp(_dense_forward(
                p["critic"], feats[:-1]).squeeze(-1))    # v(s_0..H-1)
            adv = jax.lax.stop_gradient(
                (returns - value) / new_scale)
            actor_loss = -jnp.mean(weights * (logp_as * adv
                                              + ent_scale * ents))
            total = critic_loss + actor_loss
            return total, (critic_loss, actor_loss, new_scale,
                           jnp.mean(returns))

        def train_step(params, slow_critic, wm_opt_state, ac_opt_state,
                       ret_scale, batch, key):
            k1, k2 = jax.random.split(key)
            wm_p = {k: params[k] for k in wm_keys}
            ac_p = {k: params[k] for k in ("actor", "critic")}
            (wm_l, (hs, zs, recon_l, rew_l, dyn_l)), wm_grads = \
                jax.value_and_grad(wm_loss, has_aux=True)(
                    wm_p, batch, k1)
            upd, wm_opt_state = self.wm_opt.update(
                wm_grads, wm_opt_state, wm_p)
            wm_p = optax.apply_updates(wm_p, upd)

            # imagination starts: every posterior state, flattened
            start_h = jax.lax.stop_gradient(hs.reshape(-1, D))
            start_z = jax.lax.stop_gradient(zs.reshape(-1, Z))
            (ac_l, (critic_l, actor_l, new_scale, ret_mean)), ac_grads \
                = jax.value_and_grad(ac_loss, has_aux=True)(
                    ac_p, wm_p, slow_critic, start_h, start_z,
                    ret_scale, k2)
            upd, ac_opt_state = self.ac_opt.update(
                ac_grads, ac_opt_state, ac_p)
            ac_p = optax.apply_updates(ac_p, upd)

            params = {**wm_p, **ac_p}
            slow_critic = jax.tree.map(
                lambda s, q: cfg.critic_ema_decay * s
                + (1 - cfg.critic_ema_decay) * q,
                slow_critic, {"critic": params["critic"]})
            metrics = (wm_l, recon_l, rew_l, dyn_l, critic_l, actor_l,
                       ret_mean)
            return (params, slow_critic, wm_opt_state, ac_opt_state,
                    new_scale, metrics)

        self._train_step = jax.jit(train_step)

        def act(p, h, z, obs, action_1h, key):
            k1, k2 = jax.random.split(key)
            h, z, _, _ = obs_step(p, h, z, action_1h, obs, k1)
            feat = jnp.concatenate([h, z], -1)
            logits = _dense_forward(p["actor"], feat)
            a = jax.random.categorical(k2, logits)
            return h, z, a

        self._act = jax.jit(act)
        self._obs = np.stack([env.reset(seed=cfg.seed + i)[0]
                              for i, env in enumerate(self.envs)])
        nenv = len(self.envs)
        self._h = np.zeros((nenv, D), np.float32)
        self._z = np.zeros((nenv, Z), np.float32)
        self._prev_a = np.zeros((nenv, n_act), np.float32)
        self._prev_r = np.zeros(nenv, np.float32)
        self._is_first = np.ones(nenv, bool)
        self._ep_return = np.zeros(nenv)
        self._pending_train_steps = 0.0

    # -- env interaction -------------------------------------------------
    def _collect(self, n_steps: int) -> None:
        import jax
        cfg = self.config
        obs_dim, n_act, D, K, L, Z = self._dims
        for _ in range(n_steps):
            self._key, sub = jax.random.split(self._key)
            # reset recurrent state at episode starts
            mask = (~self._is_first)[:, None].astype(np.float32)
            h, z, actions = self._act(
                self.params, self._h * mask, self._z * mask,
                self._obs, self._prev_a * mask, sub)
            self._h = np.asarray(h)
            self._z = np.asarray(z)
            actions = np.asarray(actions)
            if self.buffer.size < cfg.learning_starts:
                actions = self._rng.integers(
                    0, n_act, size=len(self.envs))
            for i, env in enumerate(self.envs):
                a = int(actions[i])
                # the row for the obs we are ACTING ON: carries the
                # action/reward that LED here (see _SeqReplay)
                self.buffer.add(self._obs[i],
                                int(np.argmax(self._prev_a[i]))
                                if self._prev_a[i].any() else 0,
                                float(self._prev_r[i]),
                                self._is_first[i], False)
                obs2, rew, term, trunc, _ = env.step(a)
                self._ep_return[i] += rew
                self._is_first[i] = False
                if term or trunc:
                    # the final observation is a real row either way —
                    # dropping it under truncation would train the
                    # reward head as if the last step paid 0
                    self.buffer.add(obs2, a, rew, False, bool(term))
                    self.record_episodes([float(self._ep_return[i])])
                    self._ep_return[i] = 0.0
                    obs2, _ = env.reset()
                    self._is_first[i] = True
                    self._prev_a[i] = 0.0
                    self._prev_r[i] = 0.0
                else:
                    self._prev_a[i] = 0.0
                    self._prev_a[i, a] = 1.0
                    self._prev_r[i] = rew
                self._obs[i] = obs2
            self._env_steps_lifetime += len(self.envs)

    def training_step(self) -> Dict[str, Any]:
        import jax
        cfg = self.config
        self._collect(cfg.rollout_fragment_length)
        metrics = None
        if self.buffer.size >= max(cfg.learning_starts,
                                   cfg.batch_length_T + 1):
            # training_ratio: replayed steps per env step ([1] table 1)
            self._pending_train_steps += (
                cfg.rollout_fragment_length * len(self.envs)
                * cfg.training_ratio
                / (cfg.batch_size_B * cfg.batch_length_T))
            n = int(self._pending_train_steps)
            self._pending_train_steps -= n
            for _ in range(max(n, 0)):
                self._key, sub = jax.random.split(self._key)
                batch = self.buffer.sample(
                    cfg.batch_size_B, cfg.batch_length_T, self._rng)
                (self.params, self.slow_critic, self.wm_opt_state,
                 self.ac_opt_state, self._ret_scale, metrics) = \
                    self._train_step(
                        self.params, self.slow_critic,
                        self.wm_opt_state, self.ac_opt_state,
                        self._ret_scale, batch, sub)
        out = {"buffer_size": self.buffer.size}
        if metrics is not None:
            names = ("world_model_loss", "recon_loss", "reward_loss",
                     "kl_dyn", "critic_loss", "actor_loss",
                     "imagined_return_mean")
            out.update({k: float(v) for k, v in zip(names, metrics)})
        return out

    def evaluate(self) -> Dict[str, Any]:
        """Greedy-ish rollouts with the recurrent policy (reference:
        the evaluation-runner split; here a simple in-process loop —
        DreamerV3 has one collection fleet, no separate eval
        runners)."""
        cfg = self.config
        env = cfg.make_python_env()
        returns = []
        try:
            for e in range(cfg.evaluation_duration):
                self.reset_single_action_state()
                obs, _ = env.reset(seed=40_000
                                   + self.iteration * 100 + e)
                total = 0.0
                for _ in range(10_000):
                    obs, rew, term, trunc, _ = env.step(
                        self.compute_single_action(obs))
                    total += rew
                    if term or trunc:
                        break
                returns.append(total)
        finally:
            env.close()
            self.reset_single_action_state()
        return {
            "episodes_this_eval": len(returns),
            "episode_return_mean": float(np.mean(returns))
            if returns else float("nan"),
        }

    def reset_single_action_state(self) -> None:
        """Start a fresh episode for compute_single_action rollouts
        (the policy is RECURRENT; callers must reset between
        episodes)."""
        self._single_state = None

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax
        obs_dim, n_act, D, K, L, Z = self._dims
        state = getattr(self, "_single_state", None)
        if state is None:
            state = (np.zeros((1, D), np.float32),
                     np.zeros((1, Z), np.float32),
                     np.zeros((1, n_act), np.float32))
        h, z, prev_a = state
        self._key, sub = jax.random.split(self._key)
        h2, z2, a = self._act(
            self.params, h, z,
            np.asarray(obs, np.float32)[None], prev_a, sub)
        a = int(np.asarray(a)[0])
        next_a = np.zeros((1, n_act), np.float32)
        next_a[0, a] = 1.0
        self._single_state = (np.asarray(h2), np.asarray(z2), next_a)
        return a

    def get_state(self) -> Dict[str, Any]:
        b = self.buffer
        n = b.size
        state = super().get_state()
        state.update(params=self.params, slow_critic=self.slow_critic,
                     wm_opt_state=self.wm_opt_state,
                     ac_opt_state=self.ac_opt_state,
                     ret_scale=self._ret_scale, key=self._key,
                     np_rng=self._rng.bit_generator.state,
                     # replay + pending train-step fraction: a restore
                     # must continue training, not silently restart
                     # warmup with an empty buffer (SAC convention)
                     buffer={
                         "obs": b.obs[:n].copy(),
                         "actions": b.actions[:n].copy(),
                         "rewards": b.rewards[:n].copy(),
                         "is_first": b.is_first[:n].copy(),
                         "terminal": b.terminal[:n].copy(),
                         "pos": b.pos, "size": n},
                     pending_train_steps=self._pending_train_steps)
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.params = state["params"]
        self.slow_critic = state["slow_critic"]
        self.wm_opt_state = state["wm_opt_state"]
        self.ac_opt_state = state["ac_opt_state"]
        self._ret_scale = state["ret_scale"]
        self._key = state["key"]
        self._rng.bit_generator.state = state["np_rng"]
        if "buffer" in state:
            buf = state["buffer"]
            n = buf["size"]
            b = self.buffer
            b.obs[:n] = buf["obs"]
            b.actions[:n] = buf["actions"]
            b.rewards[:n] = buf["rewards"]
            b.is_first[:n] = buf["is_first"]
            b.terminal[:n] = buf["terminal"]
            b.pos = buf["pos"]
            b.size = n
            self._pending_train_steps = state["pending_train_steps"]


DreamerV3Config.algo_class = DreamerV3
