"""CQL — Conservative Q-Learning for offline RL (Kumar et al. 2020).

Reference: rllib/algorithms/cql/cql.py (CQL built on SAC's torch
policies + an offline reader). Here it rides the in-tree SAC machinery
(`_SACNets` actor/critics) with the conservative penalty added to the
critic loss:

    L_CQL = alpha_cql * ( E_s[ logsumexp_a Q(s, a) ] - E_(s,a)~D[ Q ] )

where the logsumexp is estimated with importance-corrected samples from
the uniform distribution and the current policy at s and s' (the
standard CQL(H) estimator). Training is purely offline (OfflineData
minibatches); an env is used only for spaces and evaluation rollouts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithms.offline_base import (
    OfflineContinuousAlgorithm)
from ray_tpu.rl.algorithms.sac import SACConfig
from ray_tpu.rl.offline import OfflineData


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.cql_alpha = 5.0       # conservative penalty weight
        self.cql_n_actions = 10    # sampled actions per logsumexp term
        self.bc_iters = 0          # actor warmup: BC for first k updates
        self.offline_data: Optional[OfflineData] = None
        self.evaluation_episodes = 0

    def offline(self, data: OfflineData) -> "CQLConfig":
        self.offline_data = data
        return self

    def training(self, *, cql_alpha: Optional[float] = None,
                 cql_n_actions: Optional[int] = None,
                 bc_iters: Optional[int] = None, **kw) -> "CQLConfig":
        super().training(**kw)
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        if cql_n_actions is not None:
            self.cql_n_actions = int(cql_n_actions)
        if bc_iters is not None:
            self.bc_iters = int(bc_iters)
        return self

    def evaluation(self, *, evaluation_episodes: Optional[int] = None,
                   **kw) -> "CQLConfig":
        super().evaluation(**kw)  # validated explicit kwargs only
        if evaluation_episodes is not None:
            self.evaluation_episodes = int(evaluation_episodes)
        return self


class CQL(OfflineContinuousAlgorithm):
    _eval_seed_base = 20_000

    def setup(self, config: CQLConfig) -> None:
        import jax
        import jax.numpy as jnp

        nets = self._setup_common(config)
        self._finish_setup(config)
        act_dim = self.act_dim
        low, high = self.low, self.high

        gamma, tau = config.gamma, config.tau
        alpha = config.initial_alpha        # fixed entropy temperature
        cql_alpha = config.cql_alpha
        n_act = config.cql_n_actions
        # log-density of the uniform proposal over the action box
        log_u = -float(np.sum(np.log(high - low)))

        def conservative_term(p, batch, key):
            """CQL(H): E_s logsumexp_a [Q(s,a) - log q(a|s)] - E_D[Q]."""
            B = batch["obs"].shape[0]
            ku, kp, kp2 = jax.random.split(key, 3)
            # uniform proposals [n, B, A]
            a_u = jax.random.uniform(
                ku, (n_act, B, act_dim), minval=low, maxval=high)
            # policy proposals at s and s' — PROPOSALS ONLY: the
            # penalty must shape the critic, not push the actor toward
            # low-Q actions (in the reference the penalty updates only
            # critic params), so cut the gradient into the policy here
            a_pi, logp_pi = jax.lax.stop_gradient(nets.pi(
                p, jnp.broadcast_to(batch["obs"],
                                    (n_act,) + batch["obs"].shape), kp))
            a_pi2, logp_pi2 = jax.lax.stop_gradient(nets.pi(
                p, jnp.broadcast_to(batch["next_obs"],
                                    (n_act,) + batch["obs"].shape), kp2))

            def q_all(which):
                def q_one(a):
                    return nets.q(p, which, batch["obs"], a)
                q_u = jax.vmap(q_one)(a_u) - log_u
                q_p = jax.vmap(q_one)(a_pi) - logp_pi
                q_p2 = jax.vmap(q_one)(a_pi2) - logp_pi2
                stacked = jnp.concatenate([q_u, q_p, q_p2], axis=0)
                lse = jax.scipy.special.logsumexp(
                    stacked, axis=0) - jnp.log(3.0 * n_act)
                data_q = nets.q(p, which, batch["obs"], batch["actions"])
                return jnp.mean(lse) - jnp.mean(data_q)
            return q_all("q1") + q_all("q2")

        def train_step(params, target_params, opt_state, batch, key,
                       bc_mode):
            k1, k2, k3 = jax.random.split(key, 3)
            next_a, next_logp = nets.pi(params, batch["next_obs"], k1)
            q_next = jnp.minimum(
                nets.q(target_params, "q1", batch["next_obs"], next_a),
                nets.q(target_params, "q2", batch["next_obs"], next_a))
            y = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1.0 - batch["dones"])
                * (q_next - alpha * next_logp))

            def loss_fn(p):
                q1 = nets.q(p, "q1", batch["obs"], batch["actions"])
                q2 = nets.q(p, "q2", batch["obs"], batch["actions"])
                critic = (jnp.mean((q1 - y) ** 2)
                          + jnp.mean((q2 - y) ** 2))
                penalty = conservative_term(p, batch, k3)
                a, logp = nets.pi(p, batch["obs"], k2)
                if bc_mode:
                    # reference: bc_iters of behavior cloning before
                    # switching the actor to max-Q (cql.py actor
                    # warmup); mode-matching MSE stands in for logp of
                    # the squashed-Gaussian at the data action
                    actor = jnp.mean(
                        (nets.pi_mode(p, batch["obs"])
                         - batch["actions"]) ** 2)
                else:
                    q_pi = jnp.minimum(
                        nets.q(jax.lax.stop_gradient(p), "q1",
                               batch["obs"], a),
                        nets.q(jax.lax.stop_gradient(p), "q2",
                               batch["obs"], a))
                    actor = jnp.mean(alpha * logp - q_pi)
                total = critic + cql_alpha * penalty + actor
                return total, (critic, penalty, actor)

            (_, (critic_l, pen, actor_l)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state,
                                                 params)
            params = self._optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, p_: (1.0 - tau) * t + tau * p_,
                target_params, params)
            return params, target_params, opt_state, critic_l, pen, \
                actor_l

        self._train_step = jax.jit(train_step,
                                   static_argnames=("bc_mode",))

    def training_step(self) -> Dict[str, Any]:
        import jax
        cfg = self.config
        critic_l = pen = actor_l = float("nan")
        for _ in range(cfg.num_gradient_steps):
            self._key, sub = jax.random.split(self._key)
            batch = self.data.sample(cfg.train_batch_size, self._rng)
            bc_mode = self._updates < cfg.bc_iters
            (self.params, self.target_params, self.opt_state, critic_l,
             pen, actor_l) = self._train_step(
                self.params, self.target_params, self.opt_state,
                dict(batch), sub, bc_mode)
            self._updates += 1
        if cfg.evaluation_episodes:
            self.record_episodes(
                self._evaluate(cfg.evaluation_episodes))
        return {
            "critic_loss": float(critic_l),
            "cql_penalty": float(pen),
            "actor_loss": float(actor_l),
            "num_updates": self._updates,
        }


CQLConfig.algo_class = CQL
