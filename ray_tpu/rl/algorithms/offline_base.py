"""Shared base for offline continuous-control algorithms (CQL, IQL).

Both ride the SAC actor/critic nets over a fixed OfflineData set and
only touch an env for spaces + evaluation rollouts; everything below
(env/net bootstrap, deterministic evaluation, checkpoint state incl.
optimizer moments and PRNG streams) is identical between them —
reference analog: rllib's cql.py/iql.py both deriving their plumbing
from SAC/MARWIL."""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.algorithms.sac import _SACNets
from ray_tpu.rl.spaces import Box


class OfflineContinuousAlgorithm(Algorithm):
    """Env/nets/optimizer bootstrap + evaluation + checkpoint state.

    Subclasses implement ``setup`` (calling ``_setup_common`` first and
    defining their jitted train step) and ``training_step``."""

    # offset into the eval seed space so CQL/IQL rollouts never share
    # episode seeds with training or each other
    _eval_seed_base = 20_000

    def _setup_common(self, config) -> _SACNets:
        import jax
        import optax

        if config.offline_data is None:
            raise ValueError(
                f"{type(self).__name__} is offline: "
                "config.offline(OfflineData(episodes))")
        env0 = config.make_python_env()
        if not isinstance(env0.action_space, Box):
            raise ValueError(
                f"{type(self).__name__} (on SAC nets) requires a "
                "continuous action space")
        self.obs_dim = int(np.prod(env0.observation_space.shape))
        self.act_dim = int(np.prod(env0.action_space.shape))
        self.low = np.broadcast_to(
            env0.action_space.low, (self.act_dim,)).astype(np.float32)
        self.high = np.broadcast_to(
            env0.action_space.high, (self.act_dim,)).astype(np.float32)
        nets = self.nets = _SACNets(self.obs_dim, self.act_dim,
                                    config.hidden, self.low, self.high)
        self._eval_env = env0
        self.data = config.offline_data
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)
        self.params = nets.init(jax.random.PRNGKey(config.seed))
        self._updates = 0
        self._optax = optax
        return nets

    def _finish_setup(self, config) -> None:
        """Target params + optimizer over whatever ``self.params``
        holds after the subclass added its extra heads."""
        import jax
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.opt = self._optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        self._act_mode = jax.jit(self.nets.pi_mode)

    def _evaluate(self, episodes: int):
        env = self._eval_env
        returns = []
        for e in range(episodes):
            obs, _ = env.reset(seed=self._eval_seed_base
                               + self.iteration * 100 + e)
            total = 0.0
            for _ in range(1000):
                action = self.compute_single_action(obs)
                obs, rew, term, trunc, _ = env.step(action)
                total += rew
                self._env_steps_lifetime += 1
                if term or trunc:
                    break
            returns.append(total)
        return returns

    def compute_single_action(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._act_mode(self.params,
                                         np.asarray(obs)[None]))[0]

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state.update(
            params=self.params, target_params=self.target_params,
            updates=self._updates,
            # optimizer moments + PRNG streams: a restore must continue
            # training, not silently restart with fresh Adam moments
            # (same contract as SAC.get_state)
            opt_state=self.opt_state, key=self._key,
            np_rng=self._rng.bit_generator.state)
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.params = state["params"]
        self.target_params = state["target_params"]
        self._updates = state["updates"]
        if "opt_state" in state:
            self.opt_state = state["opt_state"]
            self._key = state["key"]
            self._rng.bit_generator.state = state["np_rng"]
