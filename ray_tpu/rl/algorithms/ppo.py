"""PPO (reference: rllib/algorithms/ppo/ppo.py — config defaults in
PPOConfig.__init__, surrogate loss in ppo_torch_learner.py
compute_loss_for_module).

Two sampling paths, selected automatically:
- `JaxEnv` available (e.g. "CartPole-v1") and no remote runners: the
  collect→GAE→epoch pipeline is device-resident end to end; the only
  host traffic is episode-return bookkeeping.
- Otherwise: local or remote `SingleAgentEnvRunner` actors sample
  Python envs in parallel; the learner group (possibly N actors with
  gradient allreduce) consumes the concatenated batch.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env_runner import JaxEnvRunner, SingleAgentEnvRunner
from ray_tpu.rl.learner import Learner, LearnerGroup, compute_gae
from ray_tpu.rl.sample_batch import (
    ACTIONS, ADVANTAGES, DONES, FINAL_OBS, LOGP, OBS, REWARDS,
    TRUNCATEDS, VALUE_TARGETS, VF_PREDS, SampleBatch)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.grad_clip = 0.5


class PPOLearner(Learner):
    def __init__(self, module_spec, *, clip_param=0.2, vf_clip_param=10.0,
                 vf_loss_coeff=0.5, entropy_coeff=0.01, **kwargs):
        self.clip_param = clip_param
        self.vf_clip_param = vf_clip_param
        self.vf_loss_coeff = vf_loss_coeff
        self.entropy_coeff = entropy_coeff
        super().__init__(module_spec, **kwargs)

    def loss(self, params, batch):
        import jax.numpy as jnp

        dist, values = self.spec.forward(params, batch[OBS])
        logp = dist.log_prob(batch[ACTIONS])
        ratio = jnp.exp(logp - batch[LOGP])
        adv = batch[ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surrogate = jnp.minimum(
            adv * ratio,
            adv * jnp.clip(ratio, 1 - self.clip_param, 1 + self.clip_param))
        policy_loss = -surrogate.mean()

        vf_err = (values - batch[VALUE_TARGETS]) ** 2
        vf_clipped = batch[VF_PREDS] + jnp.clip(
            values - batch[VF_PREDS], -self.vf_clip_param,
            self.vf_clip_param)
        vf_err_clipped = (vf_clipped - batch[VALUE_TARGETS]) ** 2
        vf_loss = 0.5 * jnp.maximum(vf_err, vf_err_clipped).mean()

        entropy = dist.entropy().mean()
        total = (policy_loss + self.vf_loss_coeff * vf_loss
                 - self.entropy_coeff * entropy)
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": (batch[LOGP] - logp).mean(),
        }


class _EnvRunnerActor:
    """Remote wrapper for SingleAgentEnvRunner (reference:
    env_runner_group.py actor pool)."""

    def __init__(self, blob: bytes):
        from ray_tpu.core import serialization
        kwargs = serialization.loads(blob)
        factories = kwargs.pop("connector_factories", None)
        if factories:
            from ray_tpu.rl.connectors import ConnectorPipeline
            kwargs["connectors"] = ConnectorPipeline(
                [f() for f in factories])
        self.runner = SingleAgentEnvRunner(**kwargs)

    def sample(self) -> bytes:
        from ray_tpu.core import serialization
        batch = self.runner.sample()
        # connector deltas piggyback on the payload: a separate
        # pop_connector_delta round trip would queue behind the NEXT
        # in-flight sample and turn the sync into a barrier
        return serialization.dumps((dict(batch), self.runner.pop_metrics(),
                                    self.runner.pop_connector_delta()))

    def set_weights(self, weights) -> None:
        self.runner.set_weights(weights)

    def get_connector_state(self):
        return self.runner.get_connector_state()

    def pop_connector_delta(self):
        return self.runner.pop_connector_delta()

    def set_connector_state(self, state) -> None:
        self.runner.set_connector_state(state)

    def ping(self):
        return True


class PPO(Algorithm):
    supports_multi_agent = True
    learner_cls = PPOLearner  # subclass hook (IMPALA swaps in V-trace)

    def _learner_kwargs(self, config) -> Dict[str, Any]:
        return dict(
            module_spec=self.spec, lr=config.lr,
            grad_clip=config.grad_clip, seed=config.seed,
            clip_param=config.clip_param,
            vf_clip_param=config.vf_clip_param,
            vf_loss_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff)

    def setup(self, config: PPOConfig) -> None:
        self._eval_runner = None
        if config.is_multi_agent:
            self._setup_multi_agent(config)
            return
        self.ma_runner = None
        self.spec = config.module_spec()
        self.learner_group = LearnerGroup(
            type(self).learner_cls, num_learners=config.num_learners,
            **self._learner_kwargs(config))
        self._rng = np.random.default_rng(config.seed)
        # connector sync (remote runners): one template pipeline holds
        # the driver's canonical state; rebuilt-per-step pipelines would
        # churn objects and lose the canonical accumulation
        self._connector_template = config.build_connectors()
        self._connector_state = (
            self._connector_template.get_state()
            if self._connector_template is not None else None)

        jax_env = config.make_jax_env()
        if (jax_env is not None and config.num_env_runners == 0
                and config.num_learners <= 1
                and not config.connector_factories):
            self.jax_runner = JaxEnvRunner(
                jax_env, self.spec,
                num_envs=config.num_envs_per_env_runner,
                rollout_len=config.rollout_fragment_length,
                seed=config.seed)
            self.runners = None
            return
        self.jax_runner = None
        runner_kwargs = dict(
            env_creator=(config.env_creator
                         or (lambda cfg=config: cfg.make_python_env())),
            module_spec=self.spec,
            num_envs=config.num_envs_per_env_runner,
            rollout_len=config.rollout_fragment_length)
        if config.num_env_runners == 0:
            self.runners = [SingleAgentEnvRunner(
                seed=config.seed,
                connectors=config.build_connectors(), **runner_kwargs)]
            self._remote = False
        else:
            import ray_tpu
            from ray_tpu.core import serialization
            actor_cls = ray_tpu.remote(_EnvRunnerActor)
            self._runner_actor_cls = actor_cls
            self._runner_blobs = [
                serialization.dumps(
                    dict(seed=config.seed + i,
                         connector_factories=config.connector_factories,
                         **runner_kwargs))
                for i in range(config.num_env_runners)]
            self.runners = [actor_cls.remote(blob)
                            for blob in self._runner_blobs]
            ray_tpu.get([r.ping.remote() for r in self.runners])
            self._remote = True

    # -- multi-agent (reference: multi_rl_module.py:40 module dict +
    #    per-policy learners; policy_mapping_fn routes agent streams) ---
    def _setup_multi_agent(self, config: PPOConfig) -> None:
        from ray_tpu.rl.multi_agent import (
            MultiAgentEnvRunner, TurnBasedEnvRunner, infer_module_specs)
        if (config.num_env_runners or config.num_learners > 1
                or config.connector_factories):
            raise NotImplementedError(
                "multi-agent PPO currently runs one local env runner "
                "and per-module local learners; num_env_runners, "
                "num_learners and env_to_module connectors are "
                "single-agent-only for now")
        env = config.make_multi_agent_env()
        try:
            self.ma_specs = infer_module_specs(
                env, config.policy_mapping_fn, config.policies,
                hidden=config.hidden)
        finally:
            env.close()
        self._rng = np.random.default_rng(config.seed)
        self.jax_runner = None
        self.runners = None
        self._remote = False
        self._connector_template = None
        # One PPOLearner per module (shared mapping = self-play when
        # several agents feed one module; independent learners when the
        # mapping splits them).
        self.ma_learners = {
            mid: PPOLearner(
                spec, lr=config.lr, grad_clip=config.grad_clip,
                seed=config.seed + j, clip_param=config.clip_param,
                vf_clip_param=config.vf_clip_param,
                vf_loss_coeff=config.vf_loss_coeff,
                entropy_coeff=config.entropy_coeff)
            for j, (mid, spec) in enumerate(sorted(self.ma_specs.items()))}
        self._to_train = (set(config.policies_to_train)
                          if config.policies_to_train is not None
                          else set(self.ma_specs))
        unknown = self._to_train - set(self.ma_specs)
        if unknown:
            raise ValueError(f"policies_to_train has unknown ids {unknown}")
        # Envs declaring turn_based=True (acting set varies per step)
        # get the stream-assembling runner; parallel envs keep the
        # dense one.
        runner_cls = (TurnBasedEnvRunner
                      if getattr(env, "turn_based", False)
                      else MultiAgentEnvRunner)
        self.ma_runner = runner_cls(
            config.make_multi_agent_env, self.ma_specs,
            config.policy_mapping_fn,
            num_envs=config.num_envs_per_env_runner,
            rollout_len=config.rollout_fragment_length,
            seed=config.seed)

    def _training_step_multi(self) -> Dict[str, Any]:
        cfg = self.config
        self.ma_runner.set_weights(
            {mid: lrn.get_weights()
             for mid, lrn in self.ma_learners.items()})
        batches = self.ma_runner.sample()
        metrics: Dict[str, Any] = {}
        runner_metrics = self.ma_runner.pop_metrics()
        self.record_episodes(runner_metrics["episode_returns"],
                             runner_metrics.get("episode_lens"))
        for mid, vals in runner_metrics["module_returns"].items():
            if vals:
                metrics[f"policy_reward_mean/{mid}"] = float(np.mean(vals))
        # env steps (not agent steps), once — matching the reference's
        # num_env_steps_sampled accounting. Turn-based runners report
        # the true count (it varies per sample); dense runners step
        # exactly rollout_len per env.
        self._env_steps_lifetime += getattr(
            self.ma_runner, "env_steps_last_sample",
            self.ma_runner.rollout_len * len(self.ma_runner.envs))
        for mid, cols in batches.items():
            if mid not in self._to_train:
                continue  # frozen: skip GAE/value forward entirely
            learner = self.ma_learners[mid]
            batch = self._postprocess(cols, learner.params,
                                      spec=self.ma_specs[mid])
            mb = min(cfg.minibatch_size, len(batch))
            mod_metrics: List[Dict] = []
            for _ in range(cfg.num_epochs):
                for minibatch in batch.minibatches(mb, self._rng):
                    mod_metrics.append(learner.update(minibatch))
            host = [{k: float(np.asarray(v)) for k, v in m.items()}
                    for m in mod_metrics]
            for key in host[0]:
                metrics[f"{mid}/{key}"] = float(
                    np.mean([m[key] for m in host]))
        return metrics

    # -- evaluation-runner split (reference: algorithm.py:1407) ---------
    def evaluate(self) -> Dict[str, Any]:
        """Sample `evaluation_duration` episodes on dedicated runners
        with exploration OFF; metrics stay separate from training."""
        cfg = self.config
        if self._eval_runner is None:
            if cfg.is_multi_agent:
                self._eval_runner = type(self.ma_runner)(
                    cfg.make_multi_agent_env, self.ma_specs,
                    cfg.policy_mapping_fn,
                    num_envs=cfg.evaluation_num_envs,
                    rollout_len=cfg.rollout_fragment_length,
                    seed=cfg.seed + 10_000, explore=False)
            else:
                self._eval_runner = SingleAgentEnvRunner(
                    env_creator=(cfg.env_creator
                                 or (lambda c=cfg: c.make_python_env())),
                    module_spec=self.spec,
                    num_envs=cfg.evaluation_num_envs,
                    rollout_len=cfg.rollout_fragment_length,
                    seed=cfg.seed + 10_000, explore=False,
                    connectors=cfg.build_connectors())
        if cfg.is_multi_agent:
            self._eval_runner.set_weights(
                {mid: lrn.get_weights()
                 for mid, lrn in self.ma_learners.items()})
        else:
            self._eval_runner.set_weights(self.learner_group.get_weights())
            # Stateful connectors (ObsNormalizer): evaluation must see
            # the statistics the policy was trained under, not a fresh
            # pipeline's identity transform.
            if self._connector_template is not None:
                state = (self._connector_state if self._remote
                         else self.runners[0].get_connector_state())
                self._eval_runner.set_connector_state(state)
        # Episodes begun under previous weights must not leak into this
        # measurement: restart every env.
        self._eval_runner.reset_envs()
        returns: List[float] = []
        lens: List[int] = []
        by_module: Dict[str, List[float]] = {}
        sampled = 0
        while len(returns) < cfg.evaluation_duration:
            self._eval_runner.sample()
            m = self._eval_runner.pop_metrics()
            returns.extend(m["episode_returns"])
            lens.extend(m["episode_lens"])
            for mid, vals in m.get("module_returns", {}).items():
                by_module.setdefault(mid, []).extend(vals)
            sampled += 1
            if sampled > 100:  # env never finishes an episode: bail
                break
        out = {
            "episode_return_mean": (float(np.mean(returns)) if returns
                                    else float("nan")),
            "episode_len_mean": (float(np.mean(lens)) if lens
                                 else float("nan")),
            "episodes_this_eval": len(returns),
        }
        for mid, vals in by_module.items():
            if vals:
                out[f"policy_reward_mean/{mid}"] = float(np.mean(vals))
        return out

    # ------------------------------------------------------------------
    def stop(self) -> None:
        """Release remote actors — leaked env runners would keep
        sampling (and holding CPUs) after the algorithm is done."""
        if getattr(self, "_remote", False) and self.runners:
            import ray_tpu
            for runner in self.runners:
                try:
                    ray_tpu.kill(runner)
                except Exception:  # noqa: BLE001 — runner already dead
                    import logging
                    logging.getLogger(__name__).debug(
                        "runner kill failed", exc_info=True)
        group = getattr(self, "learner_group", None)
        if group is not None and hasattr(group, "shutdown"):
            group.shutdown()

    def training_step(self) -> Dict[str, Any]:
        if self.ma_runner is not None:
            return self._training_step_multi()
        if self.jax_runner is not None:
            return self._training_step_jax()
        return self._training_step_python()

    def _postprocess(self, cols, params, spec=None) -> SampleBatch:
        """[T, N] columns -> flat [T*N] batch with GAE columns.

        Truncated episodes (time limits) must not be treated as true
        terminations: the value of the real next obs is folded into the
        reward at the boundary (reference:
        rllib/evaluation/postprocessing.py — bootstrap at truncation),
        then GAE cuts the trace at every episode end.
        """
        import jax.numpy as jnp
        spec = spec if spec is not None else self.spec
        v_final = spec.compute_values(params, cols[FINAL_OBS])
        rewards = (jnp.asarray(cols[REWARDS])
                   + self.config.gamma * v_final
                   * jnp.asarray(cols[TRUNCATEDS], jnp.float32))
        adv, targets = compute_gae(
            rewards, cols[VF_PREDS], cols[DONES],
            cols["bootstrap_value"], gamma=self.config.gamma,
            lambda_=self.config.lambda_)
        flat = {}
        for key in (OBS, ACTIONS, LOGP, VF_PREDS, REWARDS, DONES):
            arr = cols[key]
            flat[key] = np.asarray(arr).reshape((-1,) + arr.shape[2:])
        flat[ADVANTAGES] = np.asarray(adv).reshape(-1)
        flat[VALUE_TARGETS] = np.asarray(targets).reshape(-1)
        return SampleBatch(flat)

    def _sgd_epochs(self, batch: SampleBatch) -> Dict[str, Any]:
        cfg = self.config
        mb = min(cfg.minibatch_size, len(batch))
        all_metrics: List[Dict] = []
        for _ in range(cfg.num_epochs):
            for minibatch in batch.minibatches(mb, self._rng):
                all_metrics.append(self.learner_group.update(minibatch))
        from ray_tpu.rl.learner import mean_metrics
        return mean_metrics(all_metrics)

    def _training_step_jax(self) -> Dict[str, Any]:
        learner = self.learner_group.local_learner
        cols = self.jax_runner.sample_device(learner.params)
        self._env_steps_lifetime += (self.jax_runner.rollout_len
                                     * self.jax_runner.num_envs)
        self.record_episodes(self.jax_runner.pop_metrics()
                             ["episode_returns"])
        batch = self._postprocess(cols, learner.params)
        return self._sgd_epochs(batch)

    def _training_step_python(self) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import concat_samples
        weights = self.learner_group.get_weights()
        batches = []
        if self._remote:
            import ray_tpu
            from ray_tpu.core import serialization
            ray_tpu.get([r.set_weights.remote(weights)
                         for r in self.runners])
            deltas = []
            for blob in ray_tpu.get([r.sample.remote()
                                     for r in self.runners]):
                cols, metrics, delta = serialization.loads(blob)
                batches.append(self._postprocess(cols, weights))
                self.record_episodes(metrics["episode_returns"])
                deltas.append(delta)
            if self._connector_template is not None:
                # connector-state sync: each runner reported only the
                # statistics accumulated SINCE the last sync (disjoint
                # deltas, shipped with its sample payload); the driver
                # folds them into its canonical state and broadcasts —
                # merging full states would double-count shared history
                # and inflate the Welford count ~world_size× per
                # iteration (reference: rllib filter delta buffers).
                # Runs for ONE remote runner too: the canonical state
                # feeds evaluate()'s eval runner and must stay fresh.
                self._connector_state = (
                    self._connector_template.merge_states(
                        [self._connector_state] + deltas))
                for r in self.runners:  # fire-and-forget broadcast (the
                    # completed result is reclaimed after grace)
                    r.set_connector_state.remote(self._connector_state)  # graftlint: disable=GL015
        else:
            for runner in self.runners:
                runner.set_weights(weights)
                cols = runner.sample()
                batches.append(self._postprocess(cols, weights))
                self.record_episodes(runner.pop_metrics()
                                     ["episode_returns"])
        batch = concat_samples(batches)
        self._env_steps_lifetime += len(batch)
        return self._sgd_epochs(batch)


    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        if self.ma_runner is not None:
            state["ma_learners"] = {mid: lrn.get_state()
                                    for mid, lrn in self.ma_learners.items()}
        else:
            state["learner"] = self.learner_group.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        if self.ma_runner is not None:
            for mid, lrn_state in state["ma_learners"].items():
                self.ma_learners[mid].set_state(lrn_state)
        else:
            self.learner_group.set_state(state["learner"])


PPOConfig.algo_class = PPO
