"""PPO (reference: rllib/algorithms/ppo/ppo.py — config defaults in
PPOConfig.__init__, surrogate loss in ppo_torch_learner.py
compute_loss_for_module).

Two sampling paths, selected automatically:
- `JaxEnv` available (e.g. "CartPole-v1") and no remote runners: the
  collect→GAE→epoch pipeline is device-resident end to end; the only
  host traffic is episode-return bookkeeping.
- Otherwise: local or remote `SingleAgentEnvRunner` actors sample
  Python envs in parallel; the learner group (possibly N actors with
  gradient allreduce) consumes the concatenated batch.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env_runner import JaxEnvRunner, SingleAgentEnvRunner
from ray_tpu.rl.learner import Learner, LearnerGroup, compute_gae
from ray_tpu.rl.sample_batch import (
    ACTIONS, ADVANTAGES, DONES, FINAL_OBS, LOGP, OBS, REWARDS,
    TRUNCATEDS, VALUE_TARGETS, VF_PREDS, SampleBatch)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.grad_clip = 0.5


class PPOLearner(Learner):
    def __init__(self, module_spec, *, clip_param=0.2, vf_clip_param=10.0,
                 vf_loss_coeff=0.5, entropy_coeff=0.01, **kwargs):
        self.clip_param = clip_param
        self.vf_clip_param = vf_clip_param
        self.vf_loss_coeff = vf_loss_coeff
        self.entropy_coeff = entropy_coeff
        super().__init__(module_spec, **kwargs)

    def loss(self, params, batch):
        import jax.numpy as jnp

        dist, values = self.spec.forward(params, batch[OBS])
        logp = dist.log_prob(batch[ACTIONS])
        ratio = jnp.exp(logp - batch[LOGP])
        adv = batch[ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surrogate = jnp.minimum(
            adv * ratio,
            adv * jnp.clip(ratio, 1 - self.clip_param, 1 + self.clip_param))
        policy_loss = -surrogate.mean()

        vf_err = (values - batch[VALUE_TARGETS]) ** 2
        vf_clipped = batch[VF_PREDS] + jnp.clip(
            values - batch[VF_PREDS], -self.vf_clip_param,
            self.vf_clip_param)
        vf_err_clipped = (vf_clipped - batch[VALUE_TARGETS]) ** 2
        vf_loss = 0.5 * jnp.maximum(vf_err, vf_err_clipped).mean()

        entropy = dist.entropy().mean()
        total = (policy_loss + self.vf_loss_coeff * vf_loss
                 - self.entropy_coeff * entropy)
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": (batch[LOGP] - logp).mean(),
        }


class _EnvRunnerActor:
    """Remote wrapper for SingleAgentEnvRunner (reference:
    env_runner_group.py actor pool)."""

    def __init__(self, blob: bytes):
        from ray_tpu.core import serialization
        kwargs = serialization.loads(blob)
        factories = kwargs.pop("connector_factories", None)
        if factories:
            from ray_tpu.rl.connectors import ConnectorPipeline
            kwargs["connectors"] = ConnectorPipeline(
                [f() for f in factories])
        self.runner = SingleAgentEnvRunner(**kwargs)

    def sample(self) -> bytes:
        from ray_tpu.core import serialization
        batch = self.runner.sample()
        # connector deltas piggyback on the payload: a separate
        # pop_connector_delta round trip would queue behind the NEXT
        # in-flight sample and turn the sync into a barrier
        return serialization.dumps((dict(batch), self.runner.pop_metrics(),
                                    self.runner.pop_connector_delta()))

    def set_weights(self, weights) -> None:
        self.runner.set_weights(weights)

    def get_connector_state(self):
        return self.runner.get_connector_state()

    def pop_connector_delta(self):
        return self.runner.pop_connector_delta()

    def set_connector_state(self, state) -> None:
        self.runner.set_connector_state(state)

    def ping(self):
        return True


class PPO(Algorithm):
    def setup(self, config: PPOConfig) -> None:
        self.spec = config.module_spec()
        learner_kwargs = dict(
            module_spec=self.spec, lr=config.lr,
            grad_clip=config.grad_clip, seed=config.seed,
            clip_param=config.clip_param,
            vf_clip_param=config.vf_clip_param,
            vf_loss_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff)
        self.learner_group = LearnerGroup(
            PPOLearner, num_learners=config.num_learners, **learner_kwargs)
        self._rng = np.random.default_rng(config.seed)
        # connector sync (remote runners): one template pipeline holds
        # the driver's canonical state; rebuilt-per-step pipelines would
        # churn objects and lose the canonical accumulation
        self._connector_template = config.build_connectors()
        self._connector_state = (
            self._connector_template.get_state()
            if self._connector_template is not None else None)

        jax_env = config.make_jax_env()
        if (jax_env is not None and config.num_env_runners == 0
                and config.num_learners <= 1
                and not config.connector_factories):
            self.jax_runner = JaxEnvRunner(
                jax_env, self.spec,
                num_envs=config.num_envs_per_env_runner,
                rollout_len=config.rollout_fragment_length,
                seed=config.seed)
            self.runners = None
            return
        self.jax_runner = None
        runner_kwargs = dict(
            env_creator=(config.env_creator
                         or (lambda cfg=config: cfg.make_python_env())),
            module_spec=self.spec,
            num_envs=config.num_envs_per_env_runner,
            rollout_len=config.rollout_fragment_length)
        if config.num_env_runners == 0:
            self.runners = [SingleAgentEnvRunner(
                seed=config.seed,
                connectors=config.build_connectors(), **runner_kwargs)]
            self._remote = False
        else:
            import ray_tpu
            from ray_tpu.core import serialization
            actor_cls = ray_tpu.remote(_EnvRunnerActor)
            self._runner_actor_cls = actor_cls
            self._runner_blobs = [
                serialization.dumps(
                    dict(seed=config.seed + i,
                         connector_factories=config.connector_factories,
                         **runner_kwargs))
                for i in range(config.num_env_runners)]
            self.runners = [actor_cls.remote(blob)
                            for blob in self._runner_blobs]
            ray_tpu.get([r.ping.remote() for r in self.runners])
            self._remote = True

    # ------------------------------------------------------------------
    def stop(self) -> None:
        """Release remote actors — leaked env runners would keep
        sampling (and holding CPUs) after the algorithm is done."""
        if getattr(self, "_remote", False) and self.runners:
            import ray_tpu
            for runner in self.runners:
                try:
                    ray_tpu.kill(runner)
                except Exception:  # noqa: BLE001
                    pass
        group = getattr(self, "learner_group", None)
        if group is not None and hasattr(group, "shutdown"):
            group.shutdown()

    def training_step(self) -> Dict[str, Any]:
        if self.jax_runner is not None:
            return self._training_step_jax()
        return self._training_step_python()

    def _postprocess(self, cols, params) -> SampleBatch:
        """[T, N] columns -> flat [T*N] batch with GAE columns.

        Truncated episodes (time limits) must not be treated as true
        terminations: the value of the real next obs is folded into the
        reward at the boundary (reference:
        rllib/evaluation/postprocessing.py — bootstrap at truncation),
        then GAE cuts the trace at every episode end.
        """
        import jax.numpy as jnp
        v_final = self.spec.compute_values(params, cols[FINAL_OBS])
        rewards = (jnp.asarray(cols[REWARDS])
                   + self.config.gamma * v_final
                   * jnp.asarray(cols[TRUNCATEDS], jnp.float32))
        adv, targets = compute_gae(
            rewards, cols[VF_PREDS], cols[DONES],
            cols["bootstrap_value"], gamma=self.config.gamma,
            lambda_=self.config.lambda_)
        flat = {}
        for key in (OBS, ACTIONS, LOGP, VF_PREDS, REWARDS, DONES):
            arr = cols[key]
            flat[key] = np.asarray(arr).reshape((-1,) + arr.shape[2:])
        flat[ADVANTAGES] = np.asarray(adv).reshape(-1)
        flat[VALUE_TARGETS] = np.asarray(targets).reshape(-1)
        return SampleBatch(flat)

    def _sgd_epochs(self, batch: SampleBatch) -> Dict[str, Any]:
        cfg = self.config
        mb = min(cfg.minibatch_size, len(batch))
        all_metrics: List[Dict] = []
        for _ in range(cfg.num_epochs):
            for minibatch in batch.minibatches(mb, self._rng):
                all_metrics.append(self.learner_group.update(minibatch))
        import jax
        host = [{k: float(np.asarray(v)) for k, v in m.items()}
                for m in all_metrics]
        return {k: float(np.mean([m[k] for m in host])) for k in host[0]}

    def _training_step_jax(self) -> Dict[str, Any]:
        learner = self.learner_group.local_learner
        cols = self.jax_runner.sample_device(learner.params)
        self._env_steps_lifetime += (self.jax_runner.rollout_len
                                     * self.jax_runner.num_envs)
        self.record_episodes(self.jax_runner.pop_metrics()
                             ["episode_returns"])
        batch = self._postprocess(cols, learner.params)
        return self._sgd_epochs(batch)

    def _training_step_python(self) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import concat_samples
        weights = self.learner_group.get_weights()
        batches = []
        if self._remote:
            import ray_tpu
            from ray_tpu.core import serialization
            ray_tpu.get([r.set_weights.remote(weights)
                         for r in self.runners])
            deltas = []
            for blob in ray_tpu.get([r.sample.remote()
                                     for r in self.runners]):
                cols, metrics, delta = serialization.loads(blob)
                batches.append(self._postprocess(cols, weights))
                self.record_episodes(metrics["episode_returns"])
                deltas.append(delta)
            if self._connector_template is not None and len(self.runners) > 1:
                # connector-state sync: each runner reported only the
                # statistics accumulated SINCE the last sync (disjoint
                # deltas, shipped with its sample payload); the driver
                # folds them into its canonical state and broadcasts —
                # merging full states would double-count shared history
                # and inflate the Welford count ~world_size× per
                # iteration (reference: rllib filter delta buffers)
                self._connector_state = (
                    self._connector_template.merge_states(
                        [self._connector_state] + deltas))
                for r in self.runners:  # fire-and-forget broadcast
                    r.set_connector_state.remote(self._connector_state)
        else:
            for runner in self.runners:
                runner.set_weights(weights)
                cols = runner.sample()
                batches.append(self._postprocess(cols, weights))
                self.record_episodes(runner.pop_metrics()
                                     ["episode_returns"])
        batch = concat_samples(batches)
        self._env_steps_lifetime += len(batch)
        return self._sgd_epochs(batch)


    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["learner"] = self.learner_group.get_state()
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.learner_group.set_state(state["learner"])


PPOConfig.algo_class = PPO
