"""SAC — Soft Actor-Critic for continuous control.

Reference: rllib/algorithms/sac/ (SACConfig defaults, twin-Q critic
loss + squashed-Gaussian actor loss + automatic entropy temperature in
sac_torch_learner.py). TPU-first shape: the whole update — twin-Q
targets, reparameterized actor, alpha — is ONE jitted step over a
replay minibatch; target networks soft-update inside the same program
(polyak), so a gradient step is a single device dispatch.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.rl_module import _dense_forward, _dense_init
from ray_tpu.rl.spaces import Box

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005                 # polyak target coefficient
        self.train_batch_size = 256
        self.buffer_capacity = 100_000
        self.learning_starts = 1_000
        self.rollout_fragment_length = 64
        self.num_gradient_steps = 32
        self.num_envs_per_env_runner = 4
        self.initial_alpha = 1.0
        self.target_entropy: float = None  # default: -act_dim


class _SACNets:
    """Pure-function SAC networks over flat obs/action vectors."""

    def __init__(self, obs_dim: int, act_dim: int, hidden, low, high):
        self.obs_dim, self.act_dim, self.hidden = obs_dim, act_dim, hidden
        # tanh squashes to [-1, 1]; rescale to the action bounds
        self.scale = (high - low) / 2.0
        self.center = (high + low) / 2.0

    def init(self, key):
        import jax
        kp, k1, k2 = jax.random.split(key, 3)
        return {
            # policy head outputs [mean, log_std]
            "pi": _dense_init(kp, [self.obs_dim, *self.hidden,
                                   2 * self.act_dim], final_gain=0.01),
            "q1": _dense_init(k1, [self.obs_dim + self.act_dim,
                                   *self.hidden, 1]),
            "q2": _dense_init(k2, [self.obs_dim + self.act_dim,
                                   *self.hidden, 1]),
        }

    def pi(self, params, obs, key):
        """Reparameterized squashed-Gaussian sample.
        Returns (action in env bounds, log-prob with tanh correction)."""
        import jax
        import jax.numpy as jnp
        out = _dense_forward(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(key, mean.shape)
        logp_u = jnp.sum(
            -0.5 * (((u - mean) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi)), axis=-1)
        a = jnp.tanh(u)
        # tanh change of variables (the numerically stable SAC form)
        logp = logp_u - jnp.sum(
            2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
        return a * self.scale + self.center, logp

    def pi_mode(self, params, obs):
        import jax.numpy as jnp
        out = _dense_forward(params["pi"], obs)
        mean, _ = jnp.split(out, 2, axis=-1)
        return jnp.tanh(mean) * self.scale + self.center

    def q(self, params, which: str, obs, act):
        import jax.numpy as jnp
        x = jnp.concatenate([obs, act], axis=-1)
        return _dense_forward(params[which], x).squeeze(-1)


class _ContReplay:
    """Uniform replay with vector-valued actions."""

    def __init__(self, capacity: int, obs_dim: int, act_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros_like(self.obs)
        self.actions = np.zeros((capacity, act_dim), np.float32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.pos = 0
        self.size = 0

    def add(self, obs, action, reward, next_obs, done):
        p = self.pos
        self.obs[p], self.actions[p] = obs, action
        self.rewards[p], self.next_obs[p], self.dones[p] = (
            reward, next_obs, done)
        self.pos = (p + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, n: int, rng) -> Dict[str, np.ndarray]:
        idx = rng.integers(self.size, size=n)
        return {"obs": self.obs[idx], "actions": self.actions[idx],
                "rewards": self.rewards[idx],
                "next_obs": self.next_obs[idx], "dones": self.dones[idx]}


class SAC(Algorithm):
    def setup(self, config: SACConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        env0 = config.make_python_env()
        if not isinstance(env0.action_space, Box):
            raise ValueError("SAC requires a continuous (Box) action "
                             "space; use DQN/PPO for discrete")
        obs_dim = int(np.prod(env0.observation_space.shape))
        act_dim = int(np.prod(env0.action_space.shape))
        low = np.broadcast_to(env0.action_space.low, (act_dim,)).astype(
            np.float32)
        high = np.broadcast_to(env0.action_space.high, (act_dim,)).astype(
            np.float32)
        nets = self.nets = _SACNets(obs_dim, act_dim, config.hidden,
                                    low, high)
        self.envs = [env0] + [config.make_python_env()
                              for _ in range(
                                  config.num_envs_per_env_runner - 1)]
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)
        self.params = nets.init(jax.random.PRNGKey(config.seed))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.log_alpha = jnp.asarray(np.log(config.initial_alpha),
                                     jnp.float32)
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(act_dim))
        self.opt = optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        self.alpha_opt = optax.adam(config.lr)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)
        self.buffer = _ContReplay(config.buffer_capacity, obs_dim, act_dim)
        self._obs = np.stack([env.reset(seed=config.seed + i)[0]
                              for i, env in enumerate(self.envs)])
        self._ep_return = np.zeros(len(self.envs))
        gamma, tau = config.gamma, config.tau

        def train_step(params, target_params, log_alpha, opt_state,
                       alpha_opt_state, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            # critic: y = r + γ(1-d)(min_i Qtgt_i(s', a') − α log π(a'|s'))
            next_a, next_logp = nets.pi(params, batch["next_obs"], k1)
            q_next = jnp.minimum(
                nets.q(target_params, "q1", batch["next_obs"], next_a),
                nets.q(target_params, "q2", batch["next_obs"], next_a))
            y = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1.0 - batch["dones"])
                * (q_next - alpha * next_logp))

            def critic_actor_loss(p):
                q1 = nets.q(p, "q1", batch["obs"], batch["actions"])
                q2 = nets.q(p, "q2", batch["obs"], batch["actions"])
                critic = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)
                a, logp = nets.pi(p, batch["obs"], k2)
                q_pi = jnp.minimum(
                    nets.q(jax.lax.stop_gradient(p), "q1",
                           batch["obs"], a),
                    nets.q(jax.lax.stop_gradient(p), "q2",
                           batch["obs"], a))
                actor = jnp.mean(alpha * logp - q_pi)
                return critic + actor, (critic, actor, logp)

            (loss, (critic_l, actor_l, logp)), grads = jax.value_and_grad(
                critic_actor_loss, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)

            def alpha_loss(la):
                return -jnp.mean(jnp.exp(la)
                                 * jax.lax.stop_gradient(
                                     logp + target_entropy))

            a_grads = jax.grad(alpha_loss)(log_alpha)
            a_updates, alpha_opt_state = self.alpha_opt.update(
                a_grads, alpha_opt_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, a_updates)

            target_params = jax.tree.map(
                lambda t, p: (1.0 - tau) * t + tau * p,
                target_params, params)
            return (params, target_params, log_alpha, opt_state,
                    alpha_opt_state, critic_l, actor_l)

        self._train_step = jax.jit(train_step)
        self._act = jax.jit(nets.pi)
        self._act_mode = jax.jit(nets.pi_mode)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        N = len(self.envs)
        for _ in range(cfg.rollout_fragment_length):
            self._key, sub = jax.random.split(self._key)
            if self.buffer.size < cfg.learning_starts:
                actions = np.stack([
                    self._rng.uniform(self.nets.center - self.nets.scale,
                                      self.nets.center + self.nets.scale)
                    for _ in range(N)]).astype(np.float32)
            else:
                actions, _ = self._act(self.params, self._obs, sub)
                actions = np.asarray(actions)
            for i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(actions[i])
                self._ep_return[i] += rew
                self.buffer.add(self._obs[i], actions[i], rew, obs,
                                float(term))
                if term or trunc:
                    self.record_episodes([float(self._ep_return[i])])
                    self._ep_return[i] = 0.0
                    obs, _ = env.reset()
                self._obs[i] = obs
            self._env_steps_lifetime += N

        critic_l = actor_l = float("nan")
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_gradient_steps):
                self._key, sub = jax.random.split(self._key)
                batch = self.buffer.sample(cfg.train_batch_size, self._rng)
                (self.params, self.target_params, self.log_alpha,
                 self.opt_state, self.alpha_opt_state, critic_l,
                 actor_l) = self._train_step(
                    self.params, self.target_params, self.log_alpha,
                    self.opt_state, self.alpha_opt_state, batch, sub)
        import jax.numpy as jnp
        return {
            "critic_loss": float(critic_l),
            "actor_loss": float(actor_l),
            "alpha": float(jnp.exp(self.log_alpha)),
            "buffer_size": self.buffer.size,
        }

    def compute_single_action(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._act_mode(self.params, obs[None]))[0]

    def evaluate(self) -> Dict[str, Any]:
        """Deterministic (tanh-mean) episodes on a dedicated env
        (reference: algorithm.py:1407 evaluate, exploration off)."""
        from ray_tpu.rl.evaluation import evaluate_policy

        def act(obs):
            a = self._act_mode(self.params,
                               np.asarray(obs, np.float32)[None])
            return np.asarray(a)[0]

        return evaluate_policy(
            self.config.make_python_env, act,
            num_episodes=self.config.evaluation_duration)

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state.update(
            params=self.params, target_params=self.target_params,
            log_alpha=self.log_alpha,
            # optimizer moments + alpha optimizer + PRNG + replay: a
            # restore must continue training, not silently restart
            # warmup with fresh Adam moments and an empty buffer
            opt_state=self.opt_state,
            alpha_opt_state=self.alpha_opt_state,
            key=self._key,
            buffer={
                # slice to the filled region: a fresh run's checkpoint
                # must not carry capacity-many zero rows
                "obs": self.buffer.obs[:self.buffer.size].copy(),
                "next_obs": self.buffer.next_obs[:self.buffer.size].copy(),
                "actions": self.buffer.actions[:self.buffer.size].copy(),
                "rewards": self.buffer.rewards[:self.buffer.size].copy(),
                "dones": self.buffer.dones[:self.buffer.size].copy(),
                "pos": self.buffer.pos, "size": self.buffer.size,
            })
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.log_alpha = state["log_alpha"]
        if "opt_state" in state:
            self.opt_state = state["opt_state"]
            self.alpha_opt_state = state["alpha_opt_state"]
            self._key = state["key"]
            buf = state["buffer"]
            n = buf["size"]
            self.buffer.obs[:n] = buf["obs"]
            self.buffer.next_obs[:n] = buf["next_obs"]
            self.buffer.actions[:n] = buf["actions"]
            self.buffer.rewards[:n] = buf["rewards"]
            self.buffer.dones[:n] = buf["dones"]
            self.buffer.pos = buf["pos"]
            self.buffer.size = n


SACConfig.algo_class = SAC
