"""Offline RL data: episode storage + minibatch sampling.

Reference: rllib/offline/ — OfflineData reads experience datasets
(episodes of obs/actions/rewards) and feeds learner minibatches;
rllib/offline/offline_data.py + the input readers. Here episodes come
from plain dicts, a ``ray_tpu.data.Dataset`` of row-dicts, or a
running policy (``collect_episodes``), and Monte-Carlo returns are
precomputed at load so advantage-weighted methods (MARWIL) need no
bootstrapping.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch

RETURNS = "returns"


class OfflineData:
    """Flat transition store with per-transition Monte-Carlo returns."""

    def __init__(self, episodes: List[Dict[str, np.ndarray]], *,
                 gamma: float = 0.99):
        obs, actions, rewards, returns = [], [], [], []
        next_obs, dones = [], []
        for ep in episodes:
            r = np.asarray(ep["rewards"], np.float32)
            g = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + gamma * acc
                g[t] = acc
            o = np.asarray(ep["obs"], np.float32)
            obs.append(o)
            actions.append(np.asarray(ep["actions"]))
            rewards.append(r)
            returns.append(g)
            # TD columns for one-step offline methods (CQL). The final
            # transition's done comes from TERMINATION only — a
            # time-limit truncation must keep its bootstrap (masking it
            # teaches Q that value past the horizon is 0); its true
            # next obs is the episode's recorded final_obs when
            # available.
            final = np.asarray(ep.get("final_obs", o[-1]),
                               np.float32)[None]
            nxt = np.concatenate([o[1:], final], axis=0)
            d = np.zeros(len(r), np.float32)
            d[-1] = 1.0 if ep.get("terminated", True) else 0.0
            next_obs.append(nxt)
            dones.append(d)
        if not episodes:
            raise ValueError("OfflineData needs at least one episode")
        self.obs = np.concatenate(obs)
        self.actions = np.concatenate(actions)
        self.rewards = np.concatenate(rewards)
        self.returns = np.concatenate(returns)
        self.next_obs = np.concatenate(next_obs)
        self.dones = np.concatenate(dones)
        self.num_episodes = len(episodes)

    def __len__(self) -> int:
        return len(self.obs)

    def sample(self, batch_size: int, rng) -> SampleBatch:
        idx = rng.integers(len(self.obs), size=batch_size)
        return SampleBatch({
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
            RETURNS: self.returns[idx],
        })

    @staticmethod
    def from_dataset(dataset, *, gamma: float = 0.99,
                     episode_id_col: str = "episode_id") -> "OfflineData":
        """Build from a ray_tpu.data Dataset of transition rows with
        obs/actions/rewards (+ an episode id column to group by)."""
        rows = dataset.take_all()
        episodes: Dict[Any, Dict[str, list]] = {}
        for row in rows:
            ep = episodes.setdefault(
                row.get(episode_id_col, 0),
                {"obs": [], "actions": [], "rewards": []})
            ep["obs"].append(row["obs"])
            ep["actions"].append(row["actions"])
            ep["rewards"].append(row["rewards"])
        return OfflineData(list(episodes.values()), gamma=gamma)


def collect_episodes(env_creator, policy_fn, *, num_episodes: int,
                     seed: int = 0,
                     max_steps: int = 1000) -> List[Dict[str, np.ndarray]]:
    """Roll a behavior policy to build an offline dataset
    (``policy_fn(obs) -> action``)."""
    episodes = []
    env = env_creator()
    for e in range(num_episodes):
        obs, _ = env.reset(seed=seed + e)
        ep: Dict[str, list] = {"obs": [], "actions": [], "rewards": []}
        terminated = False
        for _ in range(max_steps):
            action = policy_fn(obs)
            ep["obs"].append(obs)
            ep["actions"].append(action)
            nxt, rew, term, trunc, _ = env.step(action)
            ep["rewards"].append(rew)
            obs = nxt
            if term or trunc:
                terminated = bool(term)
                break
        out = {k: np.asarray(v) for k, v in ep.items()}
        # truncation vs termination + the true final obs, so TD methods
        # (CQL) bootstrap correctly at time limits
        out["terminated"] = terminated
        out["final_obs"] = np.asarray(obs)
        episodes.append(out)
    return episodes
