"""RLModule: the model abstraction (reference:
rllib/core/rl_module/rl_module.py:256 — forward_exploration /
forward_inference / forward_train over a spaces pair).

TPU-first shape: a module is a frozen spec + pure functions
(init/forward), so the same module runs inside a jitted rollout
(`lax.scan` on device), inside the learner's pjit-sharded loss, and on a
CPU env-runner actor — no framework object crosses the jit boundary,
only the params pytree.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from ray_tpu.rl.distributions import Categorical, DiagGaussian
from ray_tpu.rl.spaces import Box, Discrete, Space


def _dense_init(key, dims, final_gain: float = 1.0):
    """Orthogonal init (the PPO-standard choice): gain sqrt(2) for
    hidden layers, `final_gain` for the output layer."""
    import jax
    keys = jax.random.split(key, len(dims) - 1)
    layers = []
    last = len(dims) - 2
    for i, (k, d_in, d_out) in enumerate(zip(keys, dims[:-1], dims[1:])):
        gain = final_gain if i == last else np.sqrt(2.0)
        w = jax.nn.initializers.orthogonal(gain)(k, (d_in, d_out))
        layers.append({"w": w, "b": jax.numpy.zeros((d_out,))})
    return layers


def _dense_forward(layers, x, activate_last=False):
    import jax.numpy as jnp
    n = len(layers)
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < n - 1 or activate_last:
            x = jnp.tanh(x)
    return x


@dataclass(frozen=True)
class RLModuleSpec:
    """Actor-critic MLP spec for a (obs_space, action_space) pair."""

    obs_space: Space = None
    action_space: Space = None
    hidden: Tuple[int, ...] = (64, 64)

    @property
    def obs_dim(self) -> int:
        return int(np.prod(self.obs_space.shape)) or 1

    @property
    def is_continuous(self) -> bool:
        return isinstance(self.action_space, Box)

    @property
    def act_dim(self) -> int:
        if self.is_continuous:
            return int(np.prod(self.action_space.shape))
        return self.action_space.n

    def init(self, key):
        import jax
        kp, kv = jax.random.split(key)
        params = {
            "pi": _dense_init(kp, [self.obs_dim, *self.hidden, self.act_dim],
                              final_gain=0.01),
            "vf": _dense_init(kv, [self.obs_dim, *self.hidden, 1],
                              final_gain=1.0),
        }
        if self.is_continuous:
            params["log_std"] = jax.numpy.zeros((self.act_dim,))
        return params

    def forward(self, params, obs):
        """obs [..., obs_dim] -> (action distribution, value [...])."""
        dist_in = _dense_forward(params["pi"], obs)
        value = _dense_forward(params["vf"], obs).squeeze(-1)
        if self.is_continuous:
            dist = DiagGaussian(dist_in, params["log_std"])
        else:
            dist = Categorical(dist_in)
        return dist, value

    def compute_values(self, params, obs):
        return _dense_forward(params["vf"], obs).squeeze(-1)
