"""Environments: a gymnasium-style Python API plus a JAX functional API.

The reference samples gymnasium envs in EnvRunner actors
(reference: rllib/env/single_agent_env_runner.py:68). gymnasium is not
in this image, so the classic-control envs the RLlib smoke tests lean on
are implemented natively. TPU-first addition: `JaxEnv`, a pure-function
env protocol whose reset/step jit and vmap, so whole rollouts run as one
compiled program (`lax.scan`) — on-device sampling the reference has no
analog for.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rl.spaces import Box, Discrete, Space


class Env:
    """Single-agent env, gymnasium calling convention."""

    observation_space: Space
    action_space: Space
    max_episode_steps: int = 10_000

    def reset(self, *, seed: Optional[int] = None) -> Tuple[Any, Dict]:
        raise NotImplementedError

    def step(self, action) -> Tuple[Any, float, bool, bool, Dict]:
        """Returns (obs, reward, terminated, truncated, info)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Classic control, numpy
# ---------------------------------------------------------------------------

_CARTPOLE_HIGH = np.array([4.8, np.inf, 0.418, np.inf], dtype=np.float32)


class CartPole(Env):
    """CartPole-v1 dynamics (pole balancing; +1 per step, 500-step cap)."""

    observation_space = Box(-_CARTPOLE_HIGH, _CARTPOLE_HIGH)
    action_space = Discrete(2)
    max_episode_steps = 500

    def __init__(self):
        self._rng = np.random.default_rng()
        self._state = None
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._t = 0
        return self._state.copy(), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(theta), np.sin(theta)
        # gravity 9.8, cart 1.0, pole 0.1 mass, pole half-length 0.5, dt 0.02
        temp = (force + 0.05 * theta_dot**2 * sinth) / 1.1
        theta_acc = (9.8 * sinth - costh * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costh**2 / 1.1))
        x_acc = temp - 0.05 * theta_acc * costh / 1.1
        x = x + 0.02 * x_dot
        x_dot = x_dot + 0.02 * x_acc
        theta = theta + 0.02 * theta_dot
        theta_dot = theta_dot + 0.02 * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], dtype=np.float32)
        self._t += 1
        terminated = bool(abs(x) > 2.4 or abs(theta) > 0.2095)
        truncated = self._t >= self.max_episode_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class Pendulum(Env):
    """Pendulum-v1 swing-up: continuous torque in [-2, 2]."""

    observation_space = Box(np.array([-1.0, -1.0, -8.0], np.float32),
                            np.array([1.0, 1.0, 8.0], np.float32))
    action_space = Box(np.array([-2.0], np.float32),
                       np.array([2.0], np.float32))
    max_episode_steps = 200

    def __init__(self):
        self._rng = np.random.default_rng()
        self._th = 0.0
        self._thdot = 0.0
        self._t = 0

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        dtype=np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        # g 10.0, m 1.0, l 1.0, dt 0.05
        thdot = thdot + (3 * 10.0 / 2 * np.sin(th) + 3.0 * u) * 0.05
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * 0.05
        self._th, self._thdot = th, thdot
        self._t += 1
        return self._obs(), -cost, False, self._t >= self.max_episode_steps, {}


# ---------------------------------------------------------------------------
# JAX functional envs — jit/vmap-able; rollouts compile to one XLA program
# ---------------------------------------------------------------------------

class JaxEnv:
    """Pure-function env: state is a pytree, reset/step are traceable.

    `step` auto-resets on episode end, the standard shape for vectorized
    `lax.scan` rollouts. It returns a dict with:
      obs        — next obs (post-reset where the episode ended)
      final_obs  — the true next obs (pre-reset), for truncation
                   bootstrapping in GAE
      reward, terminated, truncated — scalars; done = term | trunc
    """

    observation_space: Space
    action_space: Space
    max_episode_steps: int

    def reset(self, key):
        """key -> (state, obs)"""
        raise NotImplementedError

    def step(self, state, action, key):
        """(state, action, key) -> (state, out_dict) — see class doc."""
        raise NotImplementedError


class CartPoleJax(JaxEnv):
    """CartPole-v1 as pure JAX — same dynamics as `CartPole`."""

    observation_space = CartPole.observation_space
    action_space = CartPole.action_space
    max_episode_steps = 500

    def reset(self, key):
        import jax
        s = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        return {"s": s, "t": 0}, s

    def step(self, state, action, key):
        import jax.numpy as jnp
        s = state["s"]
        x, x_dot, theta, theta_dot = s[0], s[1], s[2], s[3]
        force = jnp.where(action == 1, 10.0, -10.0)
        costh, sinth = jnp.cos(theta), jnp.sin(theta)
        temp = (force + 0.05 * theta_dot**2 * sinth) / 1.1
        theta_acc = (9.8 * sinth - costh * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * costh**2 / 1.1))
        x_acc = temp - 0.05 * theta_acc * costh / 1.1
        s2 = jnp.stack([
            x + 0.02 * x_dot,
            x_dot + 0.02 * x_acc,
            theta + 0.02 * theta_dot,
            theta_dot + 0.02 * theta_acc,
        ])
        t2 = state["t"] + 1
        terminated = (jnp.abs(s2[0]) > 2.4) | (jnp.abs(s2[2]) > 0.2095)
        truncated = ~terminated & (t2 >= self.max_episode_steps)
        done = terminated | truncated
        # auto-reset: fresh state where done
        reset_state, _ = self.reset(key)
        new_s = jnp.where(done, reset_state["s"], s2)
        new_t = jnp.where(done, 0, t2)
        return {"s": new_s, "t": new_t}, {
            "obs": new_s, "final_obs": s2, "reward": 1.0,
            "terminated": terminated, "truncated": truncated}


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
}
_JAX_REGISTRY: Dict[str, Callable[[], JaxEnv]] = {
    "CartPole-v1": CartPoleJax,
}


def register_env(name: str, creator: Callable[[], Env]) -> None:
    """Reference analog: ray.tune.register_env used by RLlib configs."""
    _REGISTRY[name] = creator


def make_env(name: str) -> Env:
    if name not in _REGISTRY:
        raise ValueError(f"unknown env {name!r}; registered: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]()


def make_jax_env(name: str) -> Optional[JaxEnv]:
    creator = _JAX_REGISTRY.get(name)
    return creator() if creator else None
