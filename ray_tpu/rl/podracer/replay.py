"""Fragment replay: the Sebulba actor→learner hand-off queue.

Trajectory fragments do NOT move through this actor — env-runner actors
``ray_tpu.put`` each fragment (zero-copy node-local via the object
store) and push only ``(meta, [ref])`` here, so the queue holds object
references plus a few floats of metadata no matter how fat the
fragments are. The learner pops references and ``ray_tpu.get``s them,
which is the object-plane transfer path (node-local reads map the
shared-memory arena directly).

Backpressure is drop-oldest: a bounded deque where a push over
capacity evicts the stalest fragment (off-policy data ages badly — the
freshest fragment is always worth more than the one the learner never
got to). ``dropped`` counts evictions so the driver can see a learner
that can't keep up. Depth is therefore bounded by construction; the
backpressure test asserts exactly that.

``FragmentReplay`` is a plain thread-safe class (no actors, no jax) so
the devtools ``check`` smoke and unit tests can exercise the queue
semantics in-process; ``ReplayActor`` is the thin remote wrapper the
Sebulba pipeline deploys (named actor, looked up by learner and actors
alike).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 64


class FragmentReplay:
    """Bounded drop-oldest fragment queue. Thread-safe; non-blocking
    pops (the learner polls and records the wait as ``rl.replay_wait``
    rather than parking an actor thread)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._pushed = 0
        self._dropped = 0
        self._popped = 0

    def push(self, item: Any) -> bool:
        """Enqueue; evicts the oldest item when full. Returns True when
        the push evicted something (the producer-side overrun signal)."""
        with self._lock:
            self._pushed += 1
            dropped = len(self._items) >= self.capacity
            if dropped:
                self._items.popleft()
                self._dropped += 1
            self._items.append(item)
            return dropped

    def pop_many(self, max_items: int = 1) -> List[Any]:
        """Up to ``max_items`` fragments, oldest first; empty list when
        the queue is dry (caller decides how to wait)."""
        out: List[Any] = []
        with self._lock:
            while self._items and len(out) < max_items:
                out.append(self._items.popleft())
                self._popped += 1
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"depth": len(self._items), "capacity": self.capacity,
                    "pushed": self._pushed, "dropped": self._dropped,
                    "popped": self._popped}


class ReplayActor:
    """Remote wrapper; deployed as a named actor so every Sebulba
    participant can look it up without shipping handles around."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._replay = FragmentReplay(capacity)

    def push(self, item: Any) -> bool:
        return self._replay.push(item)

    def pop_many(self, max_items: int = 1) -> List[Any]:
        return self._replay.pop_many(max_items)

    def depth(self) -> int:
        return self._replay.depth()

    def stats(self) -> Dict[str, int]:
        return self._replay.stats()

    def ping(self) -> bool:
        return True


def create_replay_actor(capacity: int = DEFAULT_CAPACITY,
                        name: Optional[str] = None):
    """Spawn the (optionally named) replay actor and wait until live.

    The queue holds refs + metadata only — pure bookkeeping — so it
    requests no CPU share (same as serve replicas); a 1-CPU node can
    still schedule the whole Sebulba constellation."""
    import ray_tpu
    opts: dict = {"num_cpus": 0}
    if name:
        opts["name"] = name
    handle = ray_tpu.remote(ReplayActor).options(**opts).remote(capacity)
    ray_tpu.get(handle.ping.remote())
    return handle
