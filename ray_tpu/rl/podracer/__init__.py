"""Podracer RL architectures (PAPERS.md: "Podracer architectures for
scalable deep reinforcement learning").

Two ways to spend a pod:

- :class:`Anakin` — everything on device: rollout, GAE, and the PPO
  update are ONE pmapped program; the driver moves scalars only.
- :class:`Sebulba` — everything decoupled: host env-runner actors,
  a continuously-batched inference server (on ``ray_tpu.serve``), an
  object-store replay queue, and a learner that broadcasts
  version-tagged int8 weight updates mid-flight.
"""

from ray_tpu.rl.podracer.anakin import (
    Anakin,
    AnakinConfig,
    build_step,
    init_shard,
)
from ray_tpu.rl.podracer.inference import (
    PolicyInference,
    broadcast_weights,
    build_inference_app,
    dequantize_params,
    quantize_params,
)
from ray_tpu.rl.podracer.replay import (
    DEFAULT_CAPACITY,
    FragmentReplay,
    ReplayActor,
    create_replay_actor,
)
from ray_tpu.rl.podracer.sebulba import Sebulba, SebulbaConfig

__all__ = [
    "Anakin", "AnakinConfig", "build_step", "init_shard",
    "PolicyInference", "broadcast_weights", "build_inference_app",
    "dequantize_params", "quantize_params",
    "DEFAULT_CAPACITY", "FragmentReplay", "ReplayActor",
    "create_replay_actor",
    "Sebulba", "SebulbaConfig",
]
