"""Sebulba's batched policy-inference server on the serve engine.

The policy forward is a serve deployment whose ``infer`` method sits
behind ``@serve.batch``: env-runner actors submit their per-step
observation vectors through ``DeploymentHandle``s, the continuous-
batching engine accumulates them (cross-actor) up to
``MAX_BATCH_SIZE`` or ``BATCH_WAIT_S``, and ONE jitted forward runs on
the accelerator — N host actors, one MXU-width matmul. Admission
control (deployment ``max_ongoing_requests`` / ``max_queued_requests``)
bounds the actors: an overloaded server sheds with a typed, retryable
``BackpressureError`` instead of queueing unboundedly.

Weight refresh is version-tagged and mid-flight: the learner calls
:func:`broadcast_weights` with an int8 block-quantized payload (the
EQuARX transport from ``parallel/collective``), every replica
dequantizes and swaps ``(params, version)`` with one atomic rebind —
in-flight batches finish on the old weights, the next batch reads the
new tuple. No pause, no drain, no lock on the forward path. Replies
carry the serving version so actors (and the staleness bound in the
learner) always know which policy produced an action.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu import serve
from ray_tpu.util import flight_recorder

# Continuous-batching window. 32 requests is plenty of MXU width for
# vector-obs policies while keeping the accumulate window (5ms) well
# under a host env step; fixed at decoration time by @serve.batch.
MAX_BATCH_SIZE = 32
BATCH_WAIT_S = 0.005


# --- int8 weight transport (EQuARX block quantization, PR-7) -------------

def quantize_params(params) -> List[Tuple[tuple, str, tuple]]:
    """Flatten a params pytree into ``(shape, dtype, q8-payload)`` per
    leaf — the wire format of a weight push (~4x smaller than f32)."""
    import jax
    from ray_tpu.parallel.collective import _quantize_chunk
    leaves = jax.tree_util.tree_leaves(params)
    out = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        out.append((arr.shape, str(arr.dtype),
                    _quantize_chunk(arr.astype(np.float32), "int8")))
    return out

def dequantize_params(template, payload: List[Tuple[tuple, str, tuple]]):
    """Rebuild a params pytree from the wire format, using the
    receiver's own ``template`` pytree for structure."""
    import jax
    from ray_tpu.parallel.collective import _dequantize_chunk
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(leaves) != len(payload):
        raise ValueError(
            f"weight push has {len(payload)} leaves, receiver expects "
            f"{len(leaves)} — module specs out of sync")
    new_leaves = [
        _dequantize_chunk(q).reshape(shape).astype(dtype)
        for (shape, dtype, q) in payload]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


@serve.deployment(max_ongoing_requests=64, max_queued_requests=256,
                  ray_actor_options={"num_cpus": 0})
class PolicyInference:
    """Batched actor-critic forward with live weight refresh."""

    def __init__(self, spec_blob: bytes, seed: int = 0):
        import jax
        from ray_tpu.core import serialization
        self.spec = serialization.loads(spec_blob)
        params = self.spec.init(jax.random.PRNGKey(seed))
        # (params, version): ONE atomic rebind per weight push — readers
        # unpack a consistent pair, writers never block the forward.
        self._weights: Tuple[Any, int] = (params, 0)
        self._key = jax.random.PRNGKey(seed + 1)
        spec = self.spec

        def _act(params, obs, key):
            dist, value = spec.forward(params, obs)
            action = dist.sample(key)
            return action, dist.log_prob(action), value

        self._act = jax.jit(_act)

    # -- learner-facing -------------------------------------------------
    def set_weights(self, version: int, payload) -> int:
        params, _ = self._weights
        new_params = dequantize_params(params, payload)
        self._weights = (new_params, int(version))
        return int(version)

    def get_version(self) -> int:
        return self._weights[1]

    # -- actor-facing ---------------------------------------------------
    @serve.batch(max_batch_size=MAX_BATCH_SIZE,
                 batch_wait_timeout_s=BATCH_WAIT_S)
    def infer(self, obs_list: List[np.ndarray]) -> List[Dict[str, Any]]:
        """Each request is one actor's [n_envs, obs_dim] observation
        block; the realized batch concatenates across actors."""
        import jax
        params, version = self._weights
        sizes = [np.asarray(o).shape[0] for o in obs_list]
        obs = np.concatenate([np.asarray(o) for o in obs_list], axis=0)
        # only the single batcher thread touches the key: no race
        self._key, sub = jax.random.split(self._key)
        t0 = flight_recorder.clock_ns()
        actions, logp, values = self._act(params, obs, sub)
        actions = np.asarray(actions)
        logp = np.asarray(logp)
        values = np.asarray(values)
        rec = flight_recorder.RECORDER
        if rec is not None:
            rec.record("rl", "infer_batch", t0,
                       flight_recorder.clock_ns() - t0,
                       {"requests": len(obs_list), "rows": int(obs.shape[0]),
                        "version": version})
        out = []
        lo = 0
        for n in sizes:
            out.append({"actions": actions[lo:lo + n],
                        "logp": logp[lo:lo + n],
                        "values": values[lo:lo + n],
                        "version": version,
                        "batch_rows": int(obs.shape[0])})
            lo += n
        return out

    def __call__(self, obs) -> Dict[str, Any]:
        return self.infer(obs)


def build_inference_app(spec, *, seed: int = 0, num_replicas: int = 1,
                        max_ongoing_requests: int = 64,
                        max_queued_requests: int = 256,
                        name: str = "policy"):
    """Bind the inference deployment for ``serve.run``."""
    from ray_tpu.core import serialization
    dep = PolicyInference.options(
        name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        max_queued_requests=max_queued_requests)
    return dep.bind(serialization.dumps(spec), seed)


def broadcast_weights(deployment_name: str, version: int,
                      payload) -> int:
    """Push (version, int8 payload) to EVERY replica of the inference
    deployment — the router would pick one; a weight refresh must reach
    them all. Goes through the replicas' control-plane entry point
    (``handle_control_request``), which skips the max_ongoing admission
    gate: the data-plane path returns a ``Rejected`` sentinel on a
    saturated replica that only the router retries, so a weight push
    through it would silently no-op exactly when the system is loaded.
    Every reply is checked against the pushed version; returns the
    number of replicas that confirmed the update (failures are logged,
    not raised — the next push retries them)."""
    import logging

    import ray_tpu
    from ray_tpu.core import serialization
    from ray_tpu.serve.controller import CONTROLLER_NAME
    logger = logging.getLogger(__name__)
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    _version, replicas = ray_tpu.get(
        controller.get_replicas.remote(deployment_name))
    blob = serialization.dumps(((int(version), payload), {}))
    refs = [(rid, handle.handle_control_request.remote("set_weights", blob))
            for rid, handle in replicas]
    updated = 0
    failed = []
    for rid, ref in refs:
        try:
            confirmed = ray_tpu.get(ref)
        except Exception:
            logger.warning("weight push v%d failed on replica %s",
                           version, rid, exc_info=True)
            failed.append(rid)
            continue
        if confirmed == int(version):
            updated += 1
        else:
            logger.warning("weight push v%d: replica %s confirmed %r",
                           version, rid, confirmed)
            failed.append(rid)
    if failed:
        logger.warning("weight push v%d reached %d/%d replicas "
                       "(failed: %s)", version, updated, len(refs), failed)
    return updated
