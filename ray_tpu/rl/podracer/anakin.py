"""Anakin: multi-device fused rollout+update (Podracer architecture A).

The seed's `JaxEnvRunner` already compiles a whole rollout into one
vmapped `lax.scan`; Anakin lifts that scan INTO the update step and
shards the fused program across every local device with `pmap`:

    per device:  scan-rollout (T steps x N envs)  ->  GAE  ->  PPO loss
                 ->  grad  ->  pmean across devices  ->  optax update

Parameters are replicated and live in HBM for the entire run — the
driver loop moves ONLY scalar metrics. One `pstep` call is one fully-
fused XLA program per device: environment stepping, inference, and
learning never leave the accelerator, which is the whole point of the
architecture ("Podracer architectures for scalable RL", PAPERS.md §2).

Gradient sync is `lax.pmean`, or the EQuARX int8/fp8 shared-scale
`quantized_pmean` from ``parallel/collective`` when
``grad_compression`` is set (PR 7) — the same wire-cheap collective the
DDP trainer uses.

`build_step` returns the PURE per-shard step function so the multi-
device parity test can run the identical math under `jax.vmap`
(axis_name works the same) and compare against `pmap` bitwise-ish.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rl.env import JaxEnv, make_jax_env
from ray_tpu.rl.learner import compute_gae
from ray_tpu.rl.rl_module import RLModuleSpec
from ray_tpu.rl.sample_batch import (
    ACTIONS, DONES, FINAL_OBS, LOGP, OBS, REWARDS, TRUNCATEDS, VF_PREDS)
from ray_tpu.util import flight_recorder

AXIS_NAME = "anakin"


@dataclass
class AnakinConfig:
    env: str = "CartPole-v1"
    num_envs_per_device: int = 16
    rollout_len: int = 16
    hidden: Tuple[int, ...] = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: Optional[float] = 0.5
    # None | "int8" | "fp8": EQuARX-quantized gradient pmean (PR 7)
    grad_compression: Optional[str] = None
    seed: int = 0


def make_optimizer(cfg: AnakinConfig):
    import optax
    tx = [optax.clip_by_global_norm(cfg.grad_clip)] if cfg.grad_clip else []
    return optax.chain(*tx, optax.adam(cfg.lr, eps=1e-5))


def build_step(env: JaxEnv, spec: RLModuleSpec, cfg: AnakinConfig,
               axis_name: str = AXIS_NAME):
    """Pure per-shard fused step.

    ``step(params, opt_state, env_state, obs, key) ->
    (params, opt_state, env_state, obs, key, metrics)`` — run it under
    ``jax.pmap(..., axis_name=axis_name)`` for real devices or
    ``jax.vmap(..., axis_name=axis_name)`` for the single-device parity
    reference; the cross-shard pmean means both produce identical
    updates on identical inputs.
    """
    import jax
    import jax.numpy as jnp

    optimizer = make_optimizer(cfg)
    num_envs = cfg.num_envs_per_device

    def rollout(params, env_state, obs, key):
        def step_fn(carry, _):
            env_state, obs, key = carry
            key, k_act, k_env = jax.random.split(key, 3)
            dist, value = spec.forward(params, obs)
            action = dist.sample(k_act)
            logp = dist.log_prob(action)
            env_keys = jax.random.split(k_env, num_envs)
            env_state, step_out = jax.vmap(env.step)(
                env_state, action, env_keys)
            out = {OBS: obs, ACTIONS: action, LOGP: logp,
                   VF_PREDS: value,
                   REWARDS: jnp.asarray(step_out["reward"], jnp.float32),
                   DONES: step_out["terminated"] | step_out["truncated"],
                   TRUNCATEDS: step_out["truncated"],
                   FINAL_OBS: step_out["final_obs"]}
            return (env_state, step_out["obs"], key), out

        (env_state, obs, key), cols = jax.lax.scan(
            step_fn, (env_state, obs, key), None,
            length=cfg.rollout_len)
        cols["bootstrap_value"] = spec.compute_values(params, obs)
        return env_state, obs, cols

    def ppo_loss(params, batch):
        dist, values = spec.forward(params, batch[OBS])
        logp = dist.log_prob(batch[ACTIONS])
        ratio = jnp.exp(logp - batch[LOGP])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surrogate = jnp.minimum(
            adv * ratio,
            adv * jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param))
        policy_loss = -surrogate.mean()
        vf_err = (values - batch["value_targets"]) ** 2
        vf_clipped = batch[VF_PREDS] + jnp.clip(
            values - batch[VF_PREDS], -cfg.vf_clip_param,
            cfg.vf_clip_param)
        vf_loss = 0.5 * jnp.maximum(
            vf_err, (vf_clipped - batch["value_targets"]) ** 2).mean()
        entropy = dist.entropy().mean()
        total = (policy_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    def step(params, opt_state, env_state, obs, key):
        env_state, obs, cols = rollout(params, env_state, obs, key)
        # truncation bootstrapping (same treatment as PPO._postprocess):
        # time-limit ends fold the next state's value into the reward
        v_final = spec.compute_values(params, cols[FINAL_OBS])
        rewards = (cols[REWARDS] + cfg.gamma * v_final
                   * jnp.asarray(cols[TRUNCATEDS], jnp.float32))
        adv, targets = compute_gae(
            rewards, cols[VF_PREDS], cols[DONES],
            cols["bootstrap_value"], gamma=cfg.gamma,
            lambda_=cfg.lambda_)
        flat = {k: cols[k].reshape((-1,) + cols[k].shape[2:])
                for k in (OBS, ACTIONS, LOGP, VF_PREDS)}
        flat["advantages"] = adv.reshape(-1)
        flat["value_targets"] = targets.reshape(-1)

        (loss, metrics), grads = jax.value_and_grad(
            ppo_loss, has_aux=True)(params, flat)
        if cfg.grad_compression:
            from ray_tpu.parallel.collective import quantized_pmean
            grads = jax.tree.map(
                lambda g: quantized_pmean(
                    g, axis_name, dtype=cfg.grad_compression), grads)
        else:
            grads = jax.lax.pmean(grads, axis_name)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["reward_mean"] = cols[REWARDS].mean()
        metrics = jax.lax.pmean(metrics, axis_name)
        return params, opt_state, env_state, obs, metrics

    return step


def init_shard(env: JaxEnv, spec: RLModuleSpec, cfg: AnakinConfig, key):
    """Per-shard env state: ``key -> (env_state, obs)`` for
    ``num_envs_per_device`` vectorized envs (vmap/pmap over shards)."""
    import jax
    keys = jax.random.split(key, cfg.num_envs_per_device)
    return jax.vmap(env.reset)(keys)


class Anakin:
    """Driver for the pmapped fused step over the local devices.

    Params/optimizer state are replicated once and never leave HBM; the
    per-update host traffic is the metrics dict (a handful of scalars
    per device) — everything else stays put.
    """

    def __init__(self, config: AnakinConfig, devices=None):
        import jax

        self.config = config
        env = make_jax_env(config.env)
        if env is None:
            raise ValueError(
                f"no JaxEnv registered under {config.env!r} — Anakin "
                "needs a pure-function env (see ray_tpu.rl.env)")
        self.env = env
        self.spec = RLModuleSpec(env.observation_space, env.action_space,
                                 config.hidden)
        self.devices = list(devices or jax.local_devices())
        D = len(self.devices)

        step = build_step(env, self.spec, config)
        self._pstep = jax.pmap(step, axis_name=AXIS_NAME,
                               devices=self.devices)
        self._pinit = jax.pmap(
            lambda k: init_shard(env, self.spec, config, k),
            devices=self.devices)

        key = jax.random.PRNGKey(config.seed)
        k_model, k_env, k_run = jax.random.split(key, 3)
        params = self.spec.init(k_model)
        opt_state = make_optimizer(config).init(params)
        self._params = jax.device_put_replicated(params, self.devices)
        self._opt_state = jax.device_put_replicated(
            opt_state, self.devices)
        self._env_state, self._obs = self._pinit(
            jax.random.split(k_env, D))
        self._key_src = k_run
        self.env_steps = 0
        self.env_steps_per_sec = 0.0

    def _next_keys(self):
        """One fresh PRNGKey per shard per update ([D, 2])."""
        import jax
        keys = jax.random.split(self._key_src, len(self.devices) + 1)
        self._key_src = keys[0]
        return keys[1:]

    @property
    def params(self):
        """Shard-0 view of the replicated params (host copy)."""
        import jax
        return jax.tree.map(lambda x: np.asarray(x[0]), self._params)

    def train(self, num_updates: int) -> Dict[str, Any]:
        """Run fused updates; returns aggregate metrics. Only metrics
        cross the host boundary."""
        from ray_tpu.util import metrics as metrics_mod
        cfg = self.config
        D = len(self.devices)
        steps_per_update = D * cfg.num_envs_per_device * cfg.rollout_len
        last_metrics: Dict[str, Any] = {}
        t_start = time.perf_counter()
        for i in range(num_updates):
            t0 = flight_recorder.clock_ns()
            (self._params, self._opt_state, self._env_state, self._obs,
             m) = self._pstep(self._params, self._opt_state,
                              self._env_state, self._obs,
                              self._next_keys())
            last_metrics = {k: float(np.asarray(v)[0])
                            for k, v in m.items()}
            self.env_steps += steps_per_update
            rec = flight_recorder.RECORDER
            if rec is not None:
                rec.record("rl", "learn_step", t0,
                           flight_recorder.clock_ns() - t0,
                           {"arch": "anakin", "update": i,
                            "env_steps": steps_per_update})
            metrics_mod.record_batch([
                ("counter", "ray_tpu_rl_env_steps_total",
                 {"arch": "anakin"}, float(steps_per_update), None),
            ])
        wall = max(time.perf_counter() - t_start, 1e-9)
        self.env_steps_per_sec = num_updates * steps_per_update / wall
        out = dict(last_metrics)
        out.update({
            "num_updates": num_updates,
            "num_devices": D,
            "env_steps": self.env_steps,
            "env_steps_per_sec": self.env_steps_per_sec,
        })
        return out
