"""Sebulba: decoupled actor–learner RL (Podracer architecture B).

Where Anakin fuses everything into one device program, Sebulba splits
the system across the cluster and lets every part run at its own rate:

- **env-runner actors** step arbitrary Python envs on host CPUs and get
  actions from the batched inference server (``inference.py``) through
  ``DeploymentHandle``s — the serve engine's admission control is the
  natural bound on how hard they can push;
- finished trajectory fragments go into the object store
  (``ray_tpu.put``) and only ``(meta, [ref])`` lands in the bounded
  ``FragmentReplay`` actor — zero-copy for the data, drop-oldest for
  backpressure;
- a **learner actor** drains the replay queue, runs PPO updates, and
  every ``weight_push_interval`` updates broadcasts a version-tagged
  int8-quantized weight payload to every inference replica
  (:func:`~ray_tpu.rl.podracer.inference.broadcast_weights`). Actors
  pick the new policy up between fragments WITHOUT stopping sampling —
  in-flight batches finish on the old weights, the next batch reads the
  new ones.

Staleness is measured, not hoped about: every fragment carries the
policy version that produced it; the learner drops fragments whose
version lag exceeds ``max_staleness`` and exports the observed lag as
the ``ray_tpu_rl_weight_version_lag_steps`` gauge.

The driver (:class:`Sebulba`) is a thin pump: it keeps one
``sample_fragment`` call in flight per actor via ``ray_tpu.wait`` +
immediate resubmit, survives actor death (the learner never notices),
and aggregates the run summary. It never touches trajectory data.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu import exceptions, serve
from ray_tpu.util import flight_recorder

logger = logging.getLogger(__name__)

REPLAY_ACTOR_NAME = "sebulba:replay"


@dataclass
class SebulbaConfig:
    # env: registry name resolved via rl.env.make_env, or a zero-arg
    # creator callable (cloudpickled to the actors — test-local classes
    # ship by value)
    env: str = "CartPole-v1"
    env_creator: Optional[Callable[[], Any]] = None
    num_actors: int = 2
    num_envs_per_actor: int = 4
    rollout_len: int = 16
    hidden: Tuple[int, ...] = (32, 32)
    # PPO
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: Optional[float] = 0.5
    # learner consumption
    fragments_per_step: int = 2
    max_staleness: int = 8          # drop fragments lagging > this many versions
    weight_push_interval: int = 1   # broadcast every N learner updates
    replay_capacity: int = 32
    # inference serving
    num_replicas: int = 1
    max_ongoing_requests: int = 64
    max_queued_requests: int = 256
    infer_timeout_s: float = 30.0
    app_name: str = "sebulba"
    deployment_name: str = "policy"
    seed: int = 0


# ---------------------------------------------------------------------------
# env-runner actor
# ---------------------------------------------------------------------------

class _SebulbaActorImpl:
    """Host-side env runner: python envs, actions via the inference
    handle, fragments via the object store. One ``sample_fragment``
    call is one [T, N] trajectory fragment."""

    def __init__(self, blob: bytes):
        from ray_tpu.core import serialization
        kwargs = serialization.loads(blob)
        self.actor_id: int = kwargs["actor_id"]
        self.rollout_len: int = kwargs["rollout_len"]
        self.infer_timeout_s: float = kwargs["infer_timeout_s"]
        creator = kwargs["env_creator"]
        n = kwargs["num_envs"]
        seed = kwargs["seed"]
        self.envs = [creator() for _ in range(n)]
        self.handle: serve.DeploymentHandle = kwargs["handle"]
        self._replay = ray_tpu.get_actor(kwargs["replay_name"])
        self._obs = np.stack([env.reset(seed=seed + i)[0]
                              for i, env in enumerate(self.envs)])
        self._ep_return = np.zeros(n)
        self._completed: List[float] = []

    def _infer(self, obs: np.ndarray) -> Dict[str, Any]:
        """One batched-inference round trip with bounded backpressure
        retries — admission control shedding is a signal to ease off,
        not an error."""
        deadline = time.monotonic() + self.infer_timeout_s
        while True:
            try:
                return self.handle.infer.remote(obs).result(
                    timeout_s=self.infer_timeout_s)
            except serve.BackpressureError as e:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(min(max(e.retry_after_s, 0.005), 0.25))

    def sample_fragment(self) -> Dict[str, Any]:
        from ray_tpu.rl.sample_batch import (
            ACTIONS, DONES, FINAL_OBS, LOGP, OBS, REWARDS, TRUNCATEDS,
            VF_PREDS)
        T, N = self.rollout_len, len(self.envs)
        t0 = flight_recorder.clock_ns()
        cols: Dict[str, list] = {k: [] for k in
                                 (OBS, ACTIONS, LOGP, VF_PREDS, REWARDS,
                                  DONES, TRUNCATEDS, FINAL_OBS)}
        versions: List[int] = []
        batch_rows: List[int] = []
        for _ in range(T):
            reply = self._infer(self._obs)
            versions.append(int(reply["version"]))
            batch_rows.append(int(reply["batch_rows"]))
            action = np.asarray(reply["actions"])
            cols[OBS].append(self._obs.copy())
            cols[ACTIONS].append(action)
            cols[LOGP].append(np.asarray(reply["logp"]))
            cols[VF_PREDS].append(np.asarray(reply["values"]))
            rewards = np.zeros(N, dtype=np.float32)
            dones = np.zeros(N, dtype=bool)
            truncateds = np.zeros(N, dtype=bool)
            final_obs = np.zeros_like(self._obs)
            next_obs = np.zeros_like(self._obs)
            for i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(action[i])
                rewards[i] = rew
                final_obs[i] = obs
                self._ep_return[i] += rew
                if term or trunc:
                    dones[i] = True
                    truncateds[i] = trunc and not term
                    self._completed.append(float(self._ep_return[i]))
                    self._ep_return[i] = 0.0
                    obs, _ = env.reset()
                next_obs[i] = obs
            self._obs = next_obs
            cols[REWARDS].append(rewards)
            cols[DONES].append(dones)
            cols[TRUNCATEDS].append(truncateds)
            cols[FINAL_OBS].append(final_obs)
        # bootstrap values for the post-fragment obs come from the same
        # server (one more batched forward)
        boot = self._infer(self._obs)
        versions.append(int(boot["version"]))
        fragment = {k: np.stack(v) for k, v in cols.items()}
        fragment["bootstrap_value"] = np.asarray(boot["values"])
        fragment["version"] = max(versions)
        # Fragment liveness is a borrow chain, not a producer-side
        # cache: the awaited push deserializes the nested ref inside the
        # replay actor, which registers a borrowed reference (REF_ADD)
        # that pins the object while queued; the pop_many reply then
        # pins it via task-return containment until the learner's own
        # deserialized borrow takes over. The local `ref` only needs to
        # outlive this (synchronous) push.
        ref = ray_tpu.put(fragment)
        meta = {"actor_id": self.actor_id, "env_steps": T * N,
                "version": fragment["version"]}
        dropped = ray_tpu.get(self._replay.push.remote((meta, [ref])))
        rec = flight_recorder.RECORDER
        if rec is not None:
            rec.record("rl", "rollout", t0,
                       flight_recorder.clock_ns() - t0,
                       {"arch": "sebulba", "actor_id": self.actor_id,
                        "env_steps": T * N,
                        "version": fragment["version"]})
        episode_returns, self._completed = self._completed, []
        return {"actor_id": self.actor_id, "env_steps": T * N,
                "versions_observed": versions,
                "episode_returns": episode_returns,
                "batch_rows": batch_rows, "dropped": bool(dropped)}

    def ping(self) -> bool:
        return True

    def die(self) -> None:
        """Hard-exit the worker process (actor-death test hook)."""
        import os
        os._exit(1)


# ---------------------------------------------------------------------------
# learner actor
# ---------------------------------------------------------------------------

class _SebulbaLearnerImpl:
    """Drains the replay queue, runs PPO updates, broadcasts quantized
    version-tagged weights to the inference replicas."""

    def __init__(self, blob: bytes):
        from ray_tpu.core import serialization
        from ray_tpu.rl.algorithms.ppo import PPOLearner
        kwargs = serialization.loads(blob)
        cfg: SebulbaConfig = kwargs["config"]
        self.cfg = cfg
        self.learner = PPOLearner(
            kwargs["spec"], clip_param=cfg.clip_param,
            vf_clip_param=cfg.vf_clip_param,
            vf_loss_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._replay = ray_tpu.get_actor(kwargs["replay_name"])
        self.version = 0
        self.stale_dropped = 0
        self.weight_pushes = 0
        self.push_failures = 0  # pushes that missed >=1 replica
        self.last_push_ms = 0.0
        self.env_steps = 0

    def _wait_fragments(self, want: int, timeout_s: float) -> List[Any]:
        """Poll the replay queue (recording the wait as
        ``rl.replay_wait``) until ``want`` fragments arrive or the
        timeout passes — a slow start must not deadlock the step."""
        t0 = flight_recorder.clock_ns()
        deadline = time.monotonic() + timeout_s
        items: List[Any] = []
        while len(items) < want:
            got = ray_tpu.get(
                self._replay.pop_many.remote(want - len(items)))
            items.extend(got)
            if items and time.monotonic() >= deadline:
                break
            if not got:
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.005)
        rec = flight_recorder.RECORDER
        if rec is not None:
            rec.record("rl", "replay_wait", t0,
                       flight_recorder.clock_ns() - t0,
                       {"fragments": len(items)})
        return items

    def _postprocess(self, fragment: Dict[str, Any]):
        """GAE over one [T, N] fragment → flat [T*N] training columns
        (same truncation bootstrapping as PPO._postprocess)."""
        import jax.numpy as jnp
        from ray_tpu.rl.learner import compute_gae
        from ray_tpu.rl.sample_batch import (
            ACTIONS, ADVANTAGES, DONES, FINAL_OBS, LOGP, OBS, REWARDS,
            TRUNCATEDS, VALUE_TARGETS, VF_PREDS)
        cfg = self.cfg
        v_final = np.asarray(self.learner.spec.compute_values(
            self.learner.params,
            fragment[FINAL_OBS].reshape((-1,) + fragment[FINAL_OBS].shape[2:]))
        ).reshape(fragment[REWARDS].shape)
        rewards = (fragment[REWARDS] + cfg.gamma * v_final
                   * fragment[TRUNCATEDS].astype(np.float32))
        adv, targets = compute_gae(
            jnp.asarray(rewards), jnp.asarray(fragment[VF_PREDS]),
            jnp.asarray(fragment[DONES]),
            jnp.asarray(fragment["bootstrap_value"]),
            gamma=cfg.gamma, lambda_=cfg.lambda_)
        flat = {k: fragment[k].reshape((-1,) + fragment[k].shape[2:])
                for k in (OBS, ACTIONS, LOGP, VF_PREDS)}
        flat[ADVANTAGES] = np.asarray(adv).reshape(-1)
        flat[VALUE_TARGETS] = np.asarray(targets).reshape(-1)
        return flat

    def _push_weights(self) -> None:
        from ray_tpu.rl.podracer.inference import (
            broadcast_weights, quantize_params)
        t0 = flight_recorder.clock_ns()
        payload = quantize_params(self.learner.get_weights())
        updated = broadcast_weights(
            self.cfg.deployment_name, self.version, payload)
        dur = flight_recorder.clock_ns() - t0
        self.weight_pushes += 1
        if updated < self.cfg.num_replicas:
            self.push_failures += 1
        self.last_push_ms = dur / 1e6
        rec = flight_recorder.RECORDER
        if rec is not None:
            rec.record("rl", "weight_push", t0, dur,
                       {"version": self.version, "replicas_updated": updated})

    def learn_steps(self, num_steps: int, *,
                    step_timeout_s: float = 30.0) -> Dict[str, Any]:
        """Run ``num_steps`` PPO updates off the replay queue; returns
        the run summary (losses, staleness, push stats)."""
        from ray_tpu.util import metrics as metrics_mod
        cfg = self.cfg
        history: List[Dict[str, float]] = []
        lags: List[int] = []
        for _ in range(num_steps):
            items = self._wait_fragments(cfg.fragments_per_step,
                                         step_timeout_s)
            fresh: List[Dict[str, Any]] = []
            step_lags: List[int] = []
            for meta, refs in items:
                lag = self.version - int(meta["version"])
                if lag > cfg.max_staleness:
                    self.stale_dropped += 1
                    continue
                step_lags.append(lag)
                fresh.append(ray_tpu.get(refs[0]))
            if not fresh:
                continue
            t0 = flight_recorder.clock_ns()
            flats = [self._postprocess(f) for f in fresh]
            batch = {k: np.concatenate([f[k] for f in flats])
                     for k in flats[0]}
            m = self.learner.update(batch)
            self.version += 1
            step_steps = sum(int(np.prod(f["rewards"].shape))
                             for f in fresh)
            self.env_steps += step_steps
            rec = flight_recorder.RECORDER
            if rec is not None:
                rec.record("rl", "learn_step", t0,
                           flight_recorder.clock_ns() - t0,
                           {"arch": "sebulba", "version": self.version,
                            "env_steps": step_steps})
            if self.version % cfg.weight_push_interval == 0:
                self._push_weights()
            lags.extend(step_lags)
            depth = ray_tpu.get(self._replay.depth.remote())
            max_lag = max(step_lags) if step_lags else 0
            # one RPC per learner step: every rl metric rides together
            metrics_mod.record_batch([
                ("counter", "ray_tpu_rl_env_steps_total",
                 {"arch": "sebulba"}, float(step_steps), None),
                ("histogram", "ray_tpu_rl_inference_batch_size",
                 {"arch": "sebulba"}, float(batch["obs"].shape[0]), None),
                ("gauge", "ray_tpu_rl_weight_version_lag_steps",
                 {"arch": "sebulba"}, float(max_lag), None),
                ("gauge", "ray_tpu_rl_replay_queue_depth",
                 {"arch": "sebulba"}, float(depth), None),
            ])
            history.append(
                {k: float(np.asarray(v)) for k, v in m.items()})
        return {
            "history": history,
            "num_updates": self.version,
            "env_steps": self.env_steps,
            "stale_dropped": self.stale_dropped,
            "weight_pushes": self.weight_pushes,
            "push_failures": self.push_failures,
            "last_push_ms": self.last_push_ms,
            "version_lag_max": max(lags) if lags else 0,
            "version_lag_mean": float(np.mean(lags)) if lags else 0.0,
        }

    def get_version(self) -> int:
        return self.version

    def ping(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class Sebulba:
    """Wire up and pump the whole architecture: inference app, replay
    actor, env-runner actors, learner actor."""

    def __init__(self, config: SebulbaConfig):
        from ray_tpu.core import serialization
        from ray_tpu.rl.env import make_env
        from ray_tpu.rl.podracer.inference import build_inference_app
        from ray_tpu.rl.podracer.replay import create_replay_actor
        from ray_tpu.rl.rl_module import RLModuleSpec

        if not ray_tpu.is_initialized():
            raise RuntimeError("Sebulba needs ray_tpu.init() first")
        self.config = config
        creator = config.env_creator or (lambda: make_env(config.env))
        probe = creator()
        self.spec = RLModuleSpec(probe.observation_space,
                                 probe.action_space, config.hidden)

        self.handle = serve.run(
            build_inference_app(
                self.spec, seed=config.seed,
                num_replicas=config.num_replicas,
                max_ongoing_requests=config.max_ongoing_requests,
                max_queued_requests=config.max_queued_requests,
                name=config.deployment_name),
            name=config.app_name, route_prefix=None)

        self._replay_name = f"{REPLAY_ACTOR_NAME}:{config.app_name}"
        self.replay = create_replay_actor(config.replay_capacity,
                                          name=self._replay_name)

        # num_cpus=0 across the constellation: env actors block on
        # inference round trips and the learner on the replay queue, so
        # strict CPU accounting would deadlock small (even 1-CPU) nodes
        actor_cls = ray_tpu.remote(_SebulbaActorImpl).options(num_cpus=0)
        self.actors = []
        for i in range(config.num_actors):
            blob = serialization.dumps({
                "actor_id": i,
                "env_creator": creator,
                "num_envs": config.num_envs_per_actor,
                "rollout_len": config.rollout_len,
                "seed": config.seed + 1000 * (i + 1),
                "handle": self.handle,
                "replay_name": self._replay_name,
                "infer_timeout_s": config.infer_timeout_s,
            })
            self.actors.append(actor_cls.remote(blob))
        ray_tpu.get([a.ping.remote() for a in self.actors])

        learner_cls = ray_tpu.remote(_SebulbaLearnerImpl).options(num_cpus=0)
        self.learner = learner_cls.remote(serialization.dumps({
            "config": config,
            "spec": self.spec,
            "replay_name": self._replay_name,
        }))
        ray_tpu.get(self.learner.ping.remote())
        self.actor_deaths = 0

    def train(self, learner_steps: int, *,
              step_timeout_s: float = 30.0) -> Dict[str, Any]:
        """Pump actors (one in-flight fragment each, immediate resubmit
        — sampling never pauses) while the learner runs
        ``learner_steps`` updates; returns the merged summary."""
        learn_ref = self.learner.learn_steps.remote(
            learner_steps, step_timeout_s=step_timeout_s)
        pending: Dict[Any, Any] = {
            a.sample_fragment.remote(): a for a in self.actors}
        metas: List[Dict[str, Any]] = []
        versions_by_actor: Dict[int, List[int]] = {}
        episode_returns: List[float] = []
        batch_rows: List[int] = []
        t_start = time.perf_counter()
        learn_done = False
        while pending and not learn_done:
            ready, _ = ray_tpu.wait(
                list(pending) + [learn_ref], num_returns=1)
            for ref in ready:
                if ref == learn_ref:
                    learn_done = True
                    continue
                actor = pending.pop(ref)
                try:
                    meta = ray_tpu.get(ref)
                except (exceptions.ActorError,
                        exceptions.WorkerCrashedError):
                    # actor died mid-rollout: drop it, everyone else
                    # (learner included) keeps going
                    self.actor_deaths += 1
                    self.actors = [a for a in self.actors if a is not actor]
                    continue
                metas.append(meta)
                versions_by_actor.setdefault(
                    meta["actor_id"], []).extend(meta["versions_observed"])
                episode_returns.extend(meta["episode_returns"])
                batch_rows.extend(meta["batch_rows"])
                # resubmit IMMEDIATELY — the pump never leaves an actor idle
                pending[actor.sample_fragment.remote()] = actor
        wall = max(time.perf_counter() - t_start, 1e-9)
        learn_summary = ray_tpu.get(learn_ref)
        # drain in-flight fragments so shutdown doesn't race the replay
        if pending:
            ray_tpu.wait(list(pending), num_returns=len(pending),
                         timeout=step_timeout_s)
        env_steps = sum(m["env_steps"] for m in metas)
        return {
            "learner": learn_summary,
            "env_steps_sampled": env_steps,
            "env_steps_per_sec": env_steps / wall,
            "fragments": len(metas),
            "episode_returns": episode_returns,
            "versions_by_actor": versions_by_actor,
            "mean_batch_rows": float(np.mean(batch_rows))
            if batch_rows else 0.0,
            "actor_deaths": self.actor_deaths,
            "replay": ray_tpu.get(self.replay.stats.remote()),
        }

    def shutdown(self) -> None:
        for h in (*self.actors, self.learner, self.replay):
            try:
                ray_tpu.kill(h)
            except Exception:
                logger.debug("kill during shutdown failed", exc_info=True)
        try:
            serve.delete(self.config.app_name)
        except Exception:
            logger.debug("serve.delete(%s) during shutdown failed",
                         self.config.app_name, exc_info=True)
