"""Programmatic autoscaler requests.

reference: python/ray/autoscaler/sdk/sdk.py `request_resources` —
command the autoscaler to scale to accommodate a resource shape
immediately, bypassing load-based demand and the upscaling-speed cap.
The request persists (and is idempotently replaced by each call) until
cleared with an empty request.

Mechanism: the request is stored in the GCS KV
(`autoscaler/requested_resources`), so it survives autoscaler restarts
alongside the rest of the control-plane state; `StandardAutoscaler`
reads it each round and launches whatever the *total* (not free)
capacity of live+planned nodes cannot cover.
"""
import pickle
from typing import Dict, List, Optional

from ray_tpu.core import runtime as runtime_mod

KV_NAMESPACE = "autoscaler"
KV_KEY = b"requested_resources"

__all__ = ["request_resources"]


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> None:
    """Ask the autoscaler to scale the cluster up to fit the request.

    Args:
        num_cpus: shorthand for ``[{"CPU": 1}] * num_cpus``.
        bundles: resource-shape list the cluster's TOTAL capacity must
            accommodate (in-use capacity counts toward satisfaction,
            matching the reference's target-size semantics).

    Calling with neither argument clears any outstanding request.
    """
    shapes: List[Dict[str, float]] = []
    if num_cpus:
        shapes += [{"CPU": 1.0}] * int(num_cpus)
    for b in bundles or []:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"bundle must be a non-empty dict, got {b!r}")
        shapes.append({k: float(v) for k, v in b.items()})
    rt = runtime_mod.get_runtime()
    rt.gcs.kv.put(KV_KEY, pickle.dumps(shapes), namespace=KV_NAMESPACE)


def get_requested_resources(gcs) -> List[Dict[str, float]]:
    """Read the outstanding request (autoscaler side)."""
    raw = gcs.kv.get(KV_KEY, namespace=KV_NAMESPACE)
    if not raw:
        return []
    try:
        return pickle.loads(raw)
    except Exception:  # corrupt request must not wedge reconciliation
        return []
