"""StandardAutoscaler: the demand → bin-pack → launch/terminate loop.

Reference: autoscaler/_private/autoscaler.py:172 (StandardAutoscaler,
update at :367) and resource_demand_scheduler.py:100 (bin-packing unmet
demand onto hypothetical nodes of each type). One update round:

1. read unmet demand from the runtime (backlog + infeasible tasks);
2. subtract capacity already free on live nodes;
3. first-fit-decreasing pack the remainder onto copies of each node
   type (respecting max_workers) → launch list;
4. enforce min_workers;
5. terminate nodes idle longer than idle_timeout_s (never the head).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in need.items() if v > 0)


def _take(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 runtime=None):
        from ray_tpu.core import runtime as runtime_mod
        self.config = config
        self.provider = provider
        self.runtime = runtime or runtime_mod.get_runtime()
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the core round -------------------------------------------------
    def update(self) -> Dict[str, int]:
        """One reconciliation round; returns {type: launched_count}."""
        launched: Dict[str, int] = {}
        live = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        for type_name in live.values():
            counts[type_name] = counts.get(type_name, 0) + 1

        # 1-2. unmet demand minus free capacity on live nodes
        demand = self.runtime.resource_demand()
        free = [dict(r.available)
                for r in self.runtime.scheduler.snapshot().values()]
        unmet: List[Dict[str, float]] = []
        for need in sorted(demand, key=lambda d: -sum(d.values())):
            for avail in free:
                if _fits(avail, need):
                    _take(avail, need)
                    break
            else:
                unmet.append(need)

        # 3. pack the remainder onto new nodes, type by type
        to_launch: List[NodeTypeConfig] = []
        if unmet:
            virtual: List[tuple] = []  # (avail dict, node_type)
            for need in unmet:
                placed = False
                for avail, _ in virtual:
                    if _fits(avail, need):
                        _take(avail, need)
                        placed = True
                        break
                if placed:
                    continue
                for nt in self.config.node_types:
                    planned = (counts.get(nt.name, 0)
                               + sum(1 for _, t in virtual
                                     if t.name == nt.name))
                    if planned >= nt.max_workers:
                        continue
                    if _fits(dict(nt.resources), need):
                        avail = dict(nt.resources)
                        _take(avail, need)
                        virtual.append((avail, nt))
                        placed = True
                        break
                # unplaceable on any type: permanently infeasible, skip
            to_launch = [nt for _, nt in virtual]

        # cap burst size by upscaling_speed (task demand only)
        max_new = max(1, int(len(live) * self.config.upscaling_speed)) \
            if live else len(to_launch) or 1
        created: Dict[str, str] = {}
        for nt in to_launch[:max_new]:
            pid = self.provider.create_node(nt)
            created[pid] = nt.name
            launched[nt.name] = launched.get(nt.name, 0) + 1
            counts[nt.name] = counts.get(nt.name, 0) + 1

        # 3b. gang demand: queued placement groups need whole nodes /
        # slices provisioned atomically (reference: autoscaler.proto
        # GangResourceRequest; kuberay TPU slice webhooks). Gang
        # launches are EXEMPT from the upscaling_speed cap — a
        # sustained task backlog filling the capped launch list must
        # not starve a pending STRICT_SPREAD slice PG (the planner
        # already bounds launches by max_workers and subtracts units
        # still booting).
        for nt in self._plan_pending_pgs(counts, {**live, **created}):
            self.provider.create_node(nt)
            launched[nt.name] = launched.get(nt.name, 0) + 1
            counts[nt.name] = counts.get(nt.name, 0) + 1

        # 3c. explicit requests (sdk.request_resources): scale the
        # cluster so TOTAL capacity fits the requested shapes. Like
        # gang demand, exempt from the upscaling_speed cap (reference:
        # autoscaler/sdk request_resources bypasses normal rate
        # limits and persists until replaced).
        for nt in self._plan_requested_resources(counts, {**live, **created}):
            self.provider.create_node(nt)
            launched[nt.name] = launched.get(nt.name, 0) + 1
            counts[nt.name] = counts.get(nt.name, 0) + 1

        # 4. min_workers floor
        for nt in self.config.node_types:
            while counts.get(nt.name, 0) < nt.min_workers:
                self.provider.create_node(nt)
                launched[nt.name] = launched.get(nt.name, 0) + 1
                counts[nt.name] = counts.get(nt.name, 0) + 1

        # 5. idle termination
        self._terminate_idle(counts)
        return launched

    def _plan_pending_pgs(self, counts: Dict[str, int],
                          live: Dict[str, str]) -> List[NodeTypeConfig]:
        """Launch units needed to satisfy queued placement groups.

        STRICT_SPREAD/SPREAD bundles each claim a distinct host;
        PACK/STRICT_PACK bundles co-locate onto one host when they fit.
        Hosts are grouped per node type and converted to launch units
        of ``count`` hosts (a pod slice). Launch units still booting
        (provider node with no registered runtime hosts yet) count as
        incoming capacity so repeated update() rounds don't re-launch
        for the same PG.
        """
        pending = getattr(self.runtime, "pending_pg_demand", lambda: [])()
        if not pending:
            return []
        # Hosts already launched but not yet registered, per type.
        incoming: Dict[str, int] = {}
        for pid, type_name in live.items():
            if not self.provider.runtime_node_ids(pid):
                nt = self.config.node_type(type_name)
                incoming[type_name] = (incoming.get(type_name, 0)
                                       + (nt.count if nt else 1))

        hosts_needed: Dict[str, int] = {}
        for strategy, bundles in pending:
            if strategy in ("PACK", "STRICT_PACK"):
                # try to co-locate the whole gang on one host
                combined: Dict[str, float] = {}
                for b in bundles:
                    for k, v in b.items():
                        combined[k] = combined.get(k, 0.0) + v
                groups = [combined]
                if not any(_fits(dict(nt.resources), combined)
                           for nt in self.config.node_types):
                    if strategy == "STRICT_PACK":
                        continue  # infeasible on any single host
                    groups = [dict(b) for b in bundles]  # loose PACK
            else:  # SPREAD / STRICT_SPREAD: one host per bundle
                groups = [dict(b) for b in bundles]
            for need in groups:
                for nt in self.config.node_types:
                    if not _fits(dict(nt.resources), need):
                        continue
                    hosts_needed[nt.name] = hosts_needed.get(nt.name, 0) + 1
                    break
                # unplaceable on any type: permanently infeasible, skip

        launches: List[NodeTypeConfig] = []
        for type_name, hosts in hosts_needed.items():
            nt = self.config.node_type(type_name)
            if nt is None:
                continue
            hosts -= incoming.get(type_name, 0)
            if hosts <= 0:
                continue
            units = -(-hosts // max(nt.count, 1))  # ceil
            room = nt.max_workers - counts.get(type_name, 0)
            for _ in range(min(units, max(room, 0))):
                launches.append(nt)
        return launches

    def _plan_requested_resources(self, counts: Dict[str, int],
                                  live: Dict[str, str],
                                  exclude_hosts=frozenset()
                                  ) -> List[NodeTypeConfig]:
        """Launch units so total cluster capacity covers the shapes
        posted via `sdk.request_resources` (in-use capacity counts —
        these are target-size semantics, not load demand).

        The capacity pool is built without double counting: each live
        provider unit contributes its configured per-host resources
        (whether or not its hosts have registered yet), and runtime
        nodes NOT attributed to any provider unit (the head, manual
        joins) contribute their ledger totals.
        """
        from ray_tpu.autoscaler.sdk import get_requested_resources
        shapes = get_requested_resources(self.runtime.gcs)
        if not shapes:
            return []
        provider_hosts = set()
        pool: List[Dict[str, float]] = []
        for pid, type_name in live.items():
            nt = self.config.node_type(type_name)
            if nt is None:
                continue
            provider_hosts.update(self.provider.runtime_node_ids(pid))
            for _ in range(max(nt.count, 1)):
                pool.append(dict(nt.resources))
        for node_id, res in self.runtime.scheduler.snapshot().items():
            if node_id not in provider_hosts and \
                    node_id not in exclude_hosts:
                pool.append(dict(res.total))

        virtual: List[tuple] = []  # (remaining dict, node_type)
        for need in sorted(shapes, key=lambda d: -sum(d.values())):
            placed = False
            for avail in pool:
                if _fits(avail, need):
                    _take(avail, need)
                    placed = True
                    break
            if placed:
                continue
            for avail, _ in virtual:
                if _fits(avail, need):
                    _take(avail, need)
                    placed = True
                    break
            if placed:
                continue
            for nt in self.config.node_types:
                planned = (counts.get(nt.name, 0)
                           + sum(1 for _, t in virtual
                                 if t.name == nt.name))
                if planned >= nt.max_workers:
                    continue
                if _fits(dict(nt.resources), need):
                    avail = dict(nt.resources)
                    _take(avail, need)
                    virtual.append((avail, nt))
                    break
            # unplaceable on any type: permanently infeasible, skip
        return [nt for _, nt in virtual]

    def _terminate_idle(self, counts: Dict[str, int]) -> None:
        now = time.monotonic()
        snapshot = self.runtime.scheduler.snapshot()
        live = self.provider.non_terminated_nodes()
        for pid, type_name in list(live.items()):
            node_ids = self.provider.runtime_node_ids(pid)
            if not node_ids or self.runtime.head_node_id in node_ids:
                continue  # still booting, or hosts the head
            busy = False
            for node_id in node_ids:
                res = snapshot.get(node_id)
                if res is None:
                    continue
                # A node carrying placement-group bundle resources is
                # RESERVED even when no task runs — culling it would
                # silently break a gang reservation (reference:
                # placement_group_resource_manager.cc bundle holds).
                if any("_group_" in k for k in res.total):
                    busy = True
                    break
                if any(res.available.get(k, 0.0) < v - 1e-9
                       for k, v in res.total.items()):
                    busy = True
                    break
            if busy:
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            nt = self.config.node_type(type_name)
            floor = nt.min_workers if nt else 0
            if (now - first_idle >= self.config.idle_timeout_s
                    and counts.get(type_name, 0) > floor):
                # An outstanding sdk.request_resources target holds
                # capacity against scaledown: if culling this node
                # would reopen a shortfall, the next round would just
                # relaunch it — a permanent create/terminate thrash of
                # real cloud nodes (reference: request_resources pins
                # cluster size until cleared).
                counts_minus = dict(counts)
                counts_minus[type_name] = counts_minus.get(type_name, 1) - 1
                live_minus = {p: t for p, t in live.items() if p != pid}
                if self._plan_requested_resources(
                        counts_minus, live_minus,
                        exclude_hosts=frozenset(node_ids)):
                    continue  # load-bearing for the requested target
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)
                counts[type_name] = counts.get(type_name, 0) - 1
                # Keep `live` truthful for later iterations' shortfall
                # checks — a node culled above must not count as
                # capacity when judging the next candidate.
                live.pop(pid, None)

    # -- background loop ------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.update()
                except Exception:  # noqa: BLE001 — keep scaling
                    logger.exception("autoscaler update failed; "
                                     "retrying next interval")
                self._stop.wait(self.config.update_interval_s)

        self._thread = threading.Thread(target=loop, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
