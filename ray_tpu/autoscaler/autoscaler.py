"""StandardAutoscaler: the demand → bin-pack → launch/terminate loop.

Reference: autoscaler/_private/autoscaler.py:172 (StandardAutoscaler,
update at :367) and resource_demand_scheduler.py:100 (bin-packing unmet
demand onto hypothetical nodes of each type). One update round:

1. read unmet demand from the runtime (backlog + infeasible tasks);
2. subtract capacity already free on live nodes;
3. first-fit-decreasing pack the remainder onto copies of each node
   type (respecting max_workers) → launch list;
4. enforce min_workers;
5. terminate nodes idle longer than idle_timeout_s (never the head).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in need.items() if v > 0)


def _take(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 runtime=None):
        from ray_tpu.core import runtime as runtime_mod
        self.config = config
        self.provider = provider
        self.runtime = runtime or runtime_mod.get_runtime()
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the core round -------------------------------------------------
    def update(self) -> Dict[str, int]:
        """One reconciliation round; returns {type: launched_count}."""
        launched: Dict[str, int] = {}
        live = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        for type_name in live.values():
            counts[type_name] = counts.get(type_name, 0) + 1

        # 1-2. unmet demand minus free capacity on live nodes
        demand = self.runtime.resource_demand()
        free = [dict(r.available)
                for r in self.runtime.scheduler.snapshot().values()]
        unmet: List[Dict[str, float]] = []
        for need in sorted(demand, key=lambda d: -sum(d.values())):
            for avail in free:
                if _fits(avail, need):
                    _take(avail, need)
                    break
            else:
                unmet.append(need)

        # 3. pack the remainder onto new nodes, type by type
        to_launch: List[NodeTypeConfig] = []
        if unmet:
            virtual: List[tuple] = []  # (avail dict, node_type)
            for need in unmet:
                placed = False
                for avail, _ in virtual:
                    if _fits(avail, need):
                        _take(avail, need)
                        placed = True
                        break
                if placed:
                    continue
                for nt in self.config.node_types:
                    planned = (counts.get(nt.name, 0)
                               + sum(1 for _, t in virtual
                                     if t.name == nt.name))
                    if planned >= nt.max_workers:
                        continue
                    if _fits(dict(nt.resources), need):
                        avail = dict(nt.resources)
                        _take(avail, need)
                        virtual.append((avail, nt))
                        placed = True
                        break
                # unplaceable on any type: permanently infeasible, skip
            to_launch = [nt for _, nt in virtual]

        # cap burst size by upscaling_speed
        max_new = max(1, int(len(live) * self.config.upscaling_speed)) \
            if live else len(to_launch) or 1
        for nt in to_launch[:max_new]:
            self.provider.create_node(nt)
            launched[nt.name] = launched.get(nt.name, 0) + 1
            counts[nt.name] = counts.get(nt.name, 0) + 1

        # 4. min_workers floor
        for nt in self.config.node_types:
            while counts.get(nt.name, 0) < nt.min_workers:
                self.provider.create_node(nt)
                launched[nt.name] = launched.get(nt.name, 0) + 1
                counts[nt.name] = counts.get(nt.name, 0) + 1

        # 5. idle termination
        self._terminate_idle(counts)
        return launched

    def _terminate_idle(self, counts: Dict[str, int]) -> None:
        now = time.monotonic()
        snapshot = self.runtime.scheduler.snapshot()
        live = self.provider.non_terminated_nodes()
        for pid, type_name in list(live.items()):
            node_id = getattr(self.provider, "runtime_node_id",
                              lambda p: None)(pid)
            if node_id is None or node_id == self.runtime.head_node_id:
                continue
            res = snapshot.get(node_id)
            if res is None:
                continue
            busy = any(res.available.get(k, 0.0) < v - 1e-9
                       for k, v in res.total.items())
            if busy:
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            nt = self.config.node_type(type_name)
            floor = nt.min_workers if nt else 0
            if (now - first_idle >= self.config.idle_timeout_s
                    and counts.get(type_name, 0) > floor):
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)
                counts[type_name] = counts.get(type_name, 0) - 1

    # -- background loop ------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.update()
                except Exception:  # noqa: BLE001 — keep scaling
                    pass
                self._stop.wait(self.config.update_interval_s)

        self._thread = threading.Thread(target=loop, name="autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
