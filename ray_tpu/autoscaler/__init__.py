"""ray_tpu.autoscaler — demand-driven cluster scaling (reference:
python/ray/autoscaler — StandardAutoscaler v1 loop + ResourceDemandScheduler
bin-packing + pluggable NodeProviders, and the v2 instance manager)."""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.config import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.gce import GceTpuSliceNodeProvider
from ray_tpu.autoscaler.gke import GkeKubeRayNodeProvider
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider, NodeProvider)
from ray_tpu.autoscaler.policy import (
    AutoscalingPolicy, ReplicaMetrics, SLOPolicy,
    TargetOngoingRequestsPolicy, make_policy)

__all__ = [
    "AutoscalerConfig", "AutoscalingPolicy", "FakeMultiNodeProvider",
    "GceTpuSliceNodeProvider", "GkeKubeRayNodeProvider", "NodeProvider",
    "NodeTypeConfig", "ReplicaMetrics", "SLOPolicy",
    "StandardAutoscaler", "TargetOngoingRequestsPolicy", "make_policy",
]
