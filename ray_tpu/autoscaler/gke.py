"""GKE provisioning via a KubeRay-style RayCluster custom resource.

Reference: python/ray/autoscaler/_private/kuberay/node_provider.py —
the reference autoscaler scales worker groups by PATCHing the
RayCluster CR (``replicas`` up, ``replicas`` down + ``workersToDelete``)
and identifies nodes by reading the pod list; multi-host TPU slices are
worker-group replicas whose pods share a ``replicaIndex`` label (the
GKE TPU webhook's convention). This provider does the same against the
Kubernetes API server with an injectable HTTP seam (like
``gce.py``), so the gang-provisioning path (queued placement groups →
whole-slice launches) works identically on GKE.

One LAUNCH UNIT = one worker-group replica = one TPU slice (``count``
hosts = the group's ``numOfHosts``). Pod containers join the cluster by
running ``ray-tpu start`` with the slice's provider id in their labels,
exactly like the GCE startup script.
"""

from __future__ import annotations

import threading
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.config import NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.gce import PROVIDER_ID_LABEL

# KubeRay CRD group/version and the labels its operator stamps on pods
# (reference: kuberay node_provider.py KUBERAY_LABEL_KEY_TYPE /
# replicaIndex).
CRD_PATH = "/apis/ray.io/v1/namespaces/{ns}/rayclusters/{name}"
PODS_PATH = "/api/v1/namespaces/{ns}/pods"
GROUP_LABEL = "ray.io/group"
CLUSTER_LABEL = "ray.io/cluster"
REPLICA_INDEX_LABEL = "replicaIndex"

HttpRequest = Callable[[str, str, Optional[dict]],
                       Tuple[int, dict]]


def default_http_request(method: str, path: str,
                         body: Optional[dict]) -> Tuple[int, dict]:
    """In-cluster Kubernetes API call with the service-account token
    (reference: kuberay node_provider.py _get_http_headers +
    KUBERNETES_SERVICE_HOST)."""
    import json
    import os
    import ssl
    import urllib.request

    host = os.environ.get("KUBERNETES_SERVICE_HOST",
                          "kubernetes.default")
    port = os.environ.get("KUBERNETES_SERVICE_PORT_HTTPS", "443")
    token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    ca_path = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
    headers = {"Content-Type": ("application/json-patch+json"
                                if method == "PATCH"
                                else "application/json")}
    if os.path.exists(token_path):
        with open(token_path) as f:
            headers["Authorization"] = f"Bearer {f.read().strip()}"
    ctx = (ssl.create_default_context(cafile=ca_path)
           if os.path.exists(ca_path) else ssl.create_default_context())
    req = urllib.request.Request(
        f"https://{host}:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, context=ctx,
                                    timeout=60) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:  # type: ignore[attr-defined]
        try:
            payload = json.loads(e.read() or b"{}")
        except Exception:  # noqa: BLE001
            payload = {}
        return e.code, payload


class GkeKubeRayNodeProvider(NodeProvider):
    """Scale TPU slices as RayCluster worker-group replicas on GKE.

    ``create_node`` bumps the group's ``replicas`` in the CR; the
    provider id is ``{group}-{replicaIndex}`` (the index the new
    replica will take — GKE assigns 0..replicas-1 densely).
    ``terminate_node`` shrinks ``replicas`` and lists the replica's
    pods in ``workersToDelete`` so the operator removes that exact
    slice (reference: kuberay node_provider.py ScaleRequest +
    workersToDelete).
    """

    def __init__(self, namespace: str, cluster_name: str,
                 runtime=None,
                 http_request: Optional[HttpRequest] = None):
        from ray_tpu.core import runtime as runtime_mod
        self.runtime = runtime or runtime_mod.get_runtime()
        self._http = http_request or default_http_request
        self.namespace = namespace
        self.cluster_name = cluster_name
        self._crd = CRD_PATH.format(ns=namespace, name=cluster_name)
        self._lock = threading.Lock()
        # slices created this session the pod list may not show yet
        # (eventual consistency; same trick as gce.py _created)
        self._created: Dict[str, str] = {}

    # -- CR helpers ------------------------------------------------------
    def _get_cluster(self) -> dict:
        status, resp = self._http("GET", self._crd, None)
        if status >= 300:
            raise RuntimeError(
                f"RayCluster GET failed ({status}): {resp}")
        return resp

    def _group_index(self, cluster: dict, group: str) -> Tuple[int, dict]:
        specs = cluster["spec"].get("workerGroupSpecs", [])
        for idx, spec in enumerate(specs):
            if spec.get("groupName") == group:
                return idx, spec
        raise RuntimeError(
            f"worker group {group!r} not in RayCluster "
            f"{self.cluster_name!r} (has: "
            f"{[s.get('groupName') for s in specs]})")

    def _patch(self, ops: List[dict]) -> None:
        status, resp = self._http("PATCH", self._crd, ops)
        if status >= 300:
            raise RuntimeError(
                f"RayCluster PATCH failed ({status}): {resp}")

    # -- NodeProvider ----------------------------------------------------
    def create_node(self, node_type: NodeTypeConfig) -> str:
        group = node_type.name
        with self._lock:
            cluster = self._get_cluster()
            gidx, spec = self._group_index(cluster, group)
            replicas = int(spec.get("replicas", 0))
            # The new replica takes the LOWEST FREE index (the webhook
            # assigns densely and reuses freed indices) — "replicas"
            # itself collides with a live tail replica whenever a
            # non-tail one was terminated earlier.
            used = set()
            try:
                for pod in self._list_pods():
                    labels = pod.get("metadata", {}).get("labels", {})
                    if labels.get(GROUP_LABEL) == group:
                        used.add(labels.get(REPLICA_INDEX_LABEL))
            except RuntimeError:
                pass  # fall back to the local view below
            used.update(pid for pid, g in self._created.items()
                        if g == group)
            i = 0
            while f"{group}-{i}" in used:
                i += 1
            self._patch([{
                "op": "replace",
                "path": f"/spec/workerGroupSpecs/{gidx}/replicas",
                "value": replicas + 1,
            }])
            provider_id = f"{group}-{i}"
            self._created[provider_id] = group
        return provider_id

    def terminate_node(self, provider_node_id: str) -> None:
        group, _, idx = provider_node_id.rpartition("-")
        with self._lock:
            cluster = self._get_cluster()
            gidx, spec = self._group_index(cluster, group)
            replicas = int(spec.get("replicas", 0))
            pods = [p["metadata"]["name"]
                    for p in self._list_pods()
                    if p["metadata"].get("labels", {}).get(
                        REPLICA_INDEX_LABEL)
                    == provider_node_id]
            if not pods:
                # Eventual consistency: the replica's pods aren't
                # listed yet. Scaling replicas down with an empty
                # workersToDelete would make the operator remove an
                # ARBITRARY replica — defer; once the pod list shows
                # the replica, a later cull round deletes exactly it.
                self._created.pop(provider_node_id, None)
                return
            scale = spec.get("scaleStrategy", {})
            to_delete = list(scale.get("workersToDelete", ())) + pods
            self._patch([
                {"op": "replace",
                 "path": f"/spec/workerGroupSpecs/{gidx}/replicas",
                 "value": max(0, replicas - 1)},
                {"op": "replace",
                 "path": (f"/spec/workerGroupSpecs/{gidx}"
                          "/scaleStrategy"),
                 "value": {"workersToDelete": to_delete}},
            ])
            self._created.pop(provider_node_id, None)

    def _list_pods(self) -> List[dict]:
        selector = urllib.parse.quote(
            f"{CLUSTER_LABEL}={self.cluster_name}", safe="=")
        out: List[dict] = []
        token = None
        while True:
            path = (PODS_PATH.format(ns=self.namespace)
                    + f"?labelSelector={selector}")
            if token:
                path += "&continue=" + urllib.parse.quote(token, safe="")
            status, resp = self._http("GET", path, None)
            if status >= 300:
                raise RuntimeError(f"pod list failed ({status}): {resp}")
            out.extend(resp.get("items", ()))
            token = resp.get("metadata", {}).get("continue")
            if not token:
                break
        return out

    def non_terminated_nodes(self) -> Dict[str, str]:
        try:
            pods = self._list_pods()
        except RuntimeError:
            # API hiccup: local view, so one failed poll doesn't make
            # the autoscaler relaunch everything (gce.py semantics)
            with self._lock:
                return dict(self._created)
        out: Dict[str, str] = {}
        for pod in pods:
            meta = pod.get("metadata", {})
            labels = meta.get("labels", {})
            group = labels.get(GROUP_LABEL)
            rep = labels.get(REPLICA_INDEX_LABEL)
            if not group or rep is None:
                continue
            phase = pod.get("status", {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            out.setdefault(str(rep), group)
        with self._lock:
            for pid, group in self._created.items():
                out.setdefault(pid, group)
            self._created = dict(out)
        return out

    # -- runtime mapping -------------------------------------------------
    def runtime_node_ids(self, provider_node_id: str) -> List:
        out = []
        for node_id, node in list(self.runtime.nodes.items()):
            labels = getattr(node, "labels", None) or {}
            if labels.get(PROVIDER_ID_LABEL) == provider_node_id:
                out.append(node_id)
        return out
