"""Autoscaler configuration (reference: the cluster-YAML schema,
autoscaler/ray-schema.json — available_node_types with resources,
min_workers, max_workers; TPU note: a node type maps to a pod-slice
granularity, e.g. one v5p host with {"TPU": 4} + slice labels)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]   # PER HOST
    min_workers: int = 0
    max_workers: int = 10          # in LAUNCH units (slices for count>1)
    labels: Dict[str, str] = field(default_factory=dict)
    # Hosts per launch unit: a TPU pod slice provisions as ONE unit of
    # `count` hosts (e.g. v5litepod-16 = 2 hosts x 8 chips). The
    # autoscaler plans gang (placement-group) demand in hosts and
    # launches ceil(hosts/count) units (reference: slice-granular
    # scaling in _private/accelerators/tpu.py + kuberay TPU webhooks).
    count: int = 1
    # Provider-specific knobs (e.g. accelerator_type, runtime_version
    # for the GCE TPU API; reference: available_node_types.node_config
    # in the cluster YAML schema, autoscaler/ray-schema.json).
    provider_params: Dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    # max fraction of current cluster size to add per update round
    upscaling_speed: float = 1.0
    update_interval_s: float = 1.0

    def node_type(self, name: str) -> Optional[NodeTypeConfig]:
        for nt in self.node_types:
            if nt.name == name:
                return nt
        return None
