"""Node providers (reference: autoscaler/node_provider.py ABC with
aws/gcp/... implementations; FakeMultiNodeProvider at
autoscaler/_private/fake_multi_node/node_provider.py:237 simulates node
launches for tests — the pattern adopted here)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu.autoscaler.config import NodeTypeConfig


class NodeProvider:
    """Launch/terminate nodes of declared types."""

    def create_node(self, node_type: NodeTypeConfig) -> str:
        """Returns an opaque provider node id (one LAUNCH unit — a
        whole pod slice for node types with count > 1)."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider_node_id -> node_type name."""
        raise NotImplementedError

    def runtime_node_ids(self, provider_node_id: str) -> List:
        """Runtime NodeIDs of the hosts this launch unit contributed
        (empty while the unit is still booting). Default adapts the
        legacy single-node hook."""
        single = getattr(self, "runtime_node_id", None)
        if single is None:
            return []
        node_id = single(provider_node_id)
        return [node_id] if node_id is not None else []


class FakeMultiNodeProvider(NodeProvider):
    """Adds/removes simulated nodes on the live runtime — a dev-box
    stand-in for a cloud API, so autoscaling tests run hermetically
    (e.g. node types claiming {"TPU": 4} simulate v5p hosts)."""

    def __init__(self, runtime=None):
        from ray_tpu.core import runtime as runtime_mod
        self.runtime = runtime or runtime_mod.get_runtime()
        self._lock = threading.Lock()
        self._nodes: Dict[str, tuple] = {}  # pid -> (node_id, type name)
        self._counter = 0

    def create_node(self, node_type: NodeTypeConfig) -> str:
        node_id = self.runtime.add_node(
            resources=dict(node_type.resources),
            labels={"ray_tpu.io/node-type": node_type.name,
                    **node_type.labels})
        with self._lock:
            self._counter += 1
            pid = f"fake-{node_type.name}-{self._counter}"
            self._nodes[pid] = (node_id, node_type.name)
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(provider_node_id, None)
        if entry is not None:
            self.runtime.remove_node(entry[0])

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return {pid: t for pid, (_, t) in self._nodes.items()}

    def runtime_node_id(self, provider_node_id: str):
        with self._lock:
            entry = self._nodes.get(provider_node_id)
        return entry[0] if entry else None
