"""Replica autoscaling policies for the serve controller.

Reference: python/ray/serve/autoscaling_policy.py (the pluggable
policy seam) + serve/_private/autoscaling_state.py. Two policies ship:

- ``TargetOngoingRequestsPolicy`` — the reference default: desired =
  ceil(total_ongoing / target_ongoing_requests), rate-limited by the
  controller's upscale/downscale delays.
- ``SLOPolicy`` — scales on the driver-side router's admission stats
  (queue depth beyond replica capacity, windowed p99 latency) pushed
  to the controller via ``report_slo_stats``. Hysteresis is built in:
  a breach must be SUSTAINED for upscale_delay_s before replicas are
  added, and the deployment must sit comfortably below threshold
  (half of it) for downscale_delay_s before one is removed — so a
  bursty workload neither flaps up on a single spike nor flaps down
  during a lull between bursts.

The policy object is stateful (it tracks breach/calm streaks) and
lives on the controller's per-deployment state.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ReplicaMetrics:
    """One reconcile tick's view of a deployment's load."""
    running_replicas: int = 0
    # summed avg ongoing requests over replicas (replica probes)
    total_ongoing: float = 0.0
    # router-reported admission stats (may be stale or absent)
    queue_depth: float = 0.0
    p99_latency_s: float = 0.0
    ewma_queue_wait_s: float = 0.0
    stats_age_s: float = field(default=math.inf)


class AutoscalingPolicy:
    """desired_replicas() is called once per reconcile tick with fresh
    metrics; it returns the new TARGET replica count. Policies with
    ``owns_hysteresis`` apply their own damping and the controller
    adopts the returned target directly; otherwise the controller's
    upscale/downscale delay rate-limiting applies on top."""

    owns_hysteresis = False

    def desired_replicas(self, metrics: ReplicaMetrics, cfg,
                         current_target: int, now: float) -> int:
        raise NotImplementedError


class TargetOngoingRequestsPolicy(AutoscalingPolicy):
    """desired = ceil(total_ongoing / target_ongoing_requests),
    clamped to [min_replicas, max_replicas]."""

    def desired_replicas(self, metrics: ReplicaMetrics, cfg,
                         current_target: int, now: float) -> int:
        desired = int(math.ceil(
            metrics.total_ongoing
            / max(cfg.target_ongoing_requests, 1e-9))) or cfg.min_replicas
        return max(cfg.min_replicas, min(cfg.max_replicas, desired))


class SLOPolicy(AutoscalingPolicy):
    """Scale on sustained queue-depth / p99 SLO breach.

    Upscale: queue_depth > target_queue_depth (or windowed p99 >
    p99_latency_slo_s when enabled) continuously for upscale_delay_s.
    The step is proportional to how far past target the queue sits, so
    a 10x overload converges in a couple of ticks instead of one
    replica at a time. Downscale: BOTH signals at most half their
    thresholds (or stats stale — an idle router stops reporting)
    continuously for downscale_delay_s, one replica at a time.
    """

    owns_hysteresis = True

    def __init__(self):
        self._breach_since: Optional[float] = None
        self._calm_since: Optional[float] = None

    def _is_breach(self, m: ReplicaMetrics, cfg) -> bool:
        if m.stats_age_s > cfg.slo_stats_staleness_s:
            return False  # stale stats never justify adding replicas
        if m.queue_depth > cfg.target_queue_depth:
            return True
        return (cfg.p99_latency_slo_s > 0.0
                and m.p99_latency_s > cfg.p99_latency_slo_s)

    def _is_calm(self, m: ReplicaMetrics, cfg) -> bool:
        if m.stats_age_s > cfg.slo_stats_staleness_s:
            return True  # no recent traffic at all
        if m.queue_depth > 0.5 * cfg.target_queue_depth:
            return False
        return (cfg.p99_latency_slo_s <= 0.0
                or m.p99_latency_s <= 0.5 * cfg.p99_latency_slo_s)

    def desired_replicas(self, metrics: ReplicaMetrics, cfg,
                         current_target: int, now: float) -> int:
        if self._is_breach(metrics, cfg):
            self._calm_since = None
            if self._breach_since is None:
                self._breach_since = now
            if now - self._breach_since >= cfg.upscale_delay_s:
                # re-arm: the NEXT step needs its own sustained window
                self._breach_since = now
                overshoot = (metrics.queue_depth
                             / max(cfg.target_queue_depth, 1e-9))
                step = max(1, int(math.ceil(overshoot)) - 1)
                return min(cfg.max_replicas, current_target + step)
            return max(cfg.min_replicas,
                       min(cfg.max_replicas, current_target))
        self._breach_since = None
        if self._is_calm(metrics, cfg):
            if self._calm_since is None:
                self._calm_since = now
            if (current_target > cfg.min_replicas
                    and now - self._calm_since >= cfg.downscale_delay_s):
                self._calm_since = now
                return current_target - 1
        else:
            self._calm_since = None
        return max(cfg.min_replicas,
                   min(cfg.max_replicas, current_target))


def make_policy(name: str) -> AutoscalingPolicy:
    if name == "slo":
        return SLOPolicy()
    if name == "ongoing":
        return TargetOngoingRequestsPolicy()
    raise ValueError(
        f"unknown autoscaling policy {name!r}; expected 'ongoing' or "
        "'slo'")
