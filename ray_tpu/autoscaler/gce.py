"""GCE/Cloud-TPU pod-slice node provider.

Provisions TPU slices through the Cloud TPU VM API
(``tpu.googleapis.com/v2 projects.locations.nodes``), the TPU-native
analog of the reference's GCP provider
(reference: python/ray/autoscaler/_private/gcp/node_provider.py:57,
gcp/config.py). One provider node = one SLICE: the API creates all
hosts of the slice atomically, each host's startup script joins the
cluster as a ``ray-tpu start`` daemon carrying a provider-id label so
the autoscaler can map slices back to runtime nodes.

The HTTP layer is injected (``http_request``) so every code path is
testable hermetically; the default implementation uses urllib with a
GCE metadata-server token (the standard auth path on TPU VMs).
"""

from __future__ import annotations

import json
import shlex
import threading
import urllib.parse
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.config import NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider

# Label keys stamped on created slices / joining daemons.
PROVIDER_ID_LABEL = "ray_tpu.io/provider-node-id"
NODE_TYPE_LABEL = "ray_tpu.io/node-type"

HttpRequest = Callable[[str, str, Optional[dict]], Tuple[int, dict]]


def _metadata_token() -> str:
    """OAuth token from the GCE metadata server (only reachable on
    GCE/TPU VMs; tests inject http_request and never hit this)."""
    import urllib.request
    req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["access_token"]


def default_http_request(method: str, url: str,
                         body: Optional[dict]) -> Tuple[int, dict]:
    import urllib.error
    import urllib.request
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Authorization": f"Bearer {_metadata_token()}",
                 "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as err:
        payload = err.read()
        try:
            parsed = json.loads(payload) if payload else {}
        except ValueError:
            parsed = {"error": payload.decode(errors="replace")}
        return err.code, parsed


def _startup_script(head_address: str, node_type: NodeTypeConfig,
                    provider_id: str) -> str:
    """Per-host boot: join the cluster as a daemon, advertising the
    slice's provider id + node type as labels (TPU chip resources are
    self-described by TpuAcceleratorManager on the host)."""
    labels = {PROVIDER_ID_LABEL: provider_id,
              NODE_TYPE_LABEL: node_type.name, **node_type.labels}
    resources = {k: v for k, v in node_type.resources.items()
                 if k not in ("TPU",)}  # chips self-detected on-host
    return (
        "#!/bin/bash\n"
        f"ray-tpu start --address {shlex.quote(head_address)} "
        f"--labels {shlex.quote(json.dumps(labels))} "
        f"--resources {shlex.quote(json.dumps(resources))}\n")


class GceTpuSliceNodeProvider(NodeProvider):
    """Slice-granular TPU provisioner.

    ``create_node`` POSTs a TPU node (= pod slice) whose
    acceleratorType comes from ``node_type.provider_params`` (e.g.
    ``v5litepod-16``); hosts join asynchronously via startup script.
    ``runtime_node_ids`` maps a slice to the runtime nodes that carry
    its provider-id label, so the autoscaler knows when a slice has
    fully booted and when it is idle.
    """

    def __init__(self, project: str, zone: str, head_address: str,
                 runtime=None, http_request: Optional[HttpRequest] = None,
                 name_prefix: str = "ray-tpu"):
        from ray_tpu.core import runtime as runtime_mod
        self.runtime = runtime or runtime_mod.get_runtime()
        self._http = http_request or default_http_request
        self._base = (f"https://tpu.googleapis.com/v2/projects/{project}"
                      f"/locations/{zone}")
        self._head_address = head_address
        self._prefix = name_prefix
        self._lock = threading.Lock()
        # Local view of created slices (authoritative list comes from
        # the API via non_terminated_nodes; this carries node types for
        # slices created this session before the API lists them).
        self._created: Dict[str, str] = {}

    # -- NodeProvider ----------------------------------------------------
    def create_node(self, node_type: NodeTypeConfig) -> str:
        provider_id = f"{self._prefix}-{node_type.name}-{uuid.uuid4().hex[:8]}"
        params = node_type.provider_params
        body = {
            "acceleratorType": params.get("accelerator_type", "v5litepod-8"),
            "runtimeVersion": params.get("runtime_version",
                                         "tpu-ubuntu2204-base"),
            "metadata": {"startup-script": _startup_script(
                self._head_address, node_type, provider_id)},
            "labels": {"ray-tpu-node-type": node_type.name,
                       "ray-tpu-cluster": self._prefix},
        }
        if params.get("network"):
            body["networkConfig"] = {"network": params["network"],
                                     "enableExternalIps": False}
        if params.get("reserved") == "true":
            body["schedulingConfig"] = {"reserved": True}
        status, resp = self._http(
            "POST", f"{self._base}/nodes?nodeId={provider_id}", body)
        if status >= 300:
            raise RuntimeError(
                f"TPU node create failed ({status}): {resp}")
        with self._lock:
            self._created[provider_id] = node_type.name
        return provider_id

    def terminate_node(self, provider_node_id: str) -> None:
        status, resp = self._http(
            "DELETE", f"{self._base}/nodes/{provider_node_id}", None)
        if status >= 300 and status != 404:
            raise RuntimeError(
                f"TPU node delete failed ({status}): {resp}")
        with self._lock:
            self._created.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        page_token = None
        while True:
            url = f"{self._base}/nodes"
            if page_token:
                url += "?pageToken=" + urllib.parse.quote(
                    page_token, safe="")
            status, resp = self._http("GET", url, None)
            if status >= 300:
                # API hiccup: fall back to the local view so one failed
                # poll doesn't make the autoscaler relaunch everything.
                with self._lock:
                    return dict(self._created)
            for node in resp.get("nodes", ()):
                if node.get("state") in ("DELETING", "TERMINATED",
                                         "STOPPED"):
                    continue
                name = node.get("name", "").rsplit("/", 1)[-1]
                if not name.startswith(self._prefix):
                    continue
                labels = node.get("labels", {})
                node_type = labels.get("ray-tpu-node-type", "")
                out[name] = node_type
            page_token = resp.get("nextPageToken")
            if not page_token:
                break
        with self._lock:
            # adopt API truth; keep just-created entries the API may
            # not list yet (eventual consistency)
            for pid, t in self._created.items():
                out.setdefault(pid, t)
            self._created = dict(out)
        return out

    # -- runtime mapping -------------------------------------------------
    def runtime_node_ids(self, provider_node_id: str) -> List:
        out = []
        for node_id, node in list(self.runtime.nodes.items()):
            labels = getattr(node, "labels", None) or {}
            if labels.get(PROVIDER_ID_LABEL) == provider_node_id:
                out.append(node_id)
        return out
