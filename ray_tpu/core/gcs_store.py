"""Pluggable GCS storage: in-memory or file-backed journal.

Capability parity with the reference's GCS store clients
(reference: src/ray/gcs/store_client/in_memory_store_client.h and
redis_store_client.h — Redis gives the reference GCS fault tolerance;
state is replayed on restart via gcs_init_data.cc). Here the durable
backend is an append-only journal file with snapshot compaction: every
table mutation appends one record; on restart the journal replays into
a fresh Gcs, so control-plane state (KV, jobs, functions, named actors)
survives the head process.
"""

from __future__ import annotations

import os
import pickle
import threading

from ray_tpu.devtools import locktrace
from typing import Any, Callable, Dict, Iterator, Optional, Tuple


class FileStoreClient:
    """Append-only journal of (table, op, key, value) records."""

    COMPACT_EVERY = 5000  # appended ops between snapshot compactions

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = locktrace.traced_lock("core.gcs_store")
        self._state: Dict[str, Dict[Any, Any]] = {}
        if os.path.exists(path):
            self._replay_into_state()
        self._file = open(path, "ab")
        self._ops_since_compact = 0

    # --- write path -----------------------------------------------------
    def put(self, table: str, key: Any, value: Any) -> None:
        blob = pickle.dumps(("put", table, key, value), protocol=5)
        with self._lock:
            # state first: compaction (triggered below) rewrites the
            # journal FROM state, so the triggering record must already
            # be applied or it would vanish from disk
            self._state.setdefault(table, {})[key] = value
            self._append_locked(blob)

    def delete(self, table: str, key: Any) -> None:
        blob = pickle.dumps(("del", table, key, None), protocol=5)
        with self._lock:
            self._state.get(table, {}).pop(key, None)
            self._append_locked(blob)

    def _append_locked(self, blob: bytes) -> None:
        # caller holds self._lock (the _locked suffix is the contract)
        self._file.write(len(blob).to_bytes(4, "little") + blob)
        self._file.flush()
        self._ops_since_compact += 1  # graftlint: disable=GL001
        if self._ops_since_compact >= self.COMPACT_EVERY:
            self._compact_locked()

    # --- read path ------------------------------------------------------
    def get(self, table: str, key: Any) -> Optional[Any]:
        with self._lock:
            return self._state.get(table, {}).get(key)

    def items(self, table: str) -> Dict[Any, Any]:
        with self._lock:
            return dict(self._state.get(table, {}))

    def tables(self) -> Dict[str, Dict[Any, Any]]:
        with self._lock:
            return {t: dict(entries) for t, entries in self._state.items()}

    # --- journal mechanics ----------------------------------------------
    def _iter_journal(self) -> Iterator[Tuple]:
        with open(self.path, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    return
                length = int.from_bytes(header, "little")
                blob = f.read(length)
                if len(blob) < length:
                    return  # torn tail write (crash mid-append): drop it
                try:
                    yield pickle.loads(blob)
                except Exception:  # noqa: BLE001 — corrupt record
                    return

    def _replay_into_state(self) -> None:
        # __init__-time replay: single-threaded, nothing else holds a
        # reference to this store yet
        for record in self._iter_journal():
            op, table, key, value = record
            if op == "put":
                self._state.setdefault(  # graftlint: disable=GL001
                    table, {})[key] = value
            elif op == "del":
                self._state.get(table, {}).pop(key, None)

    def _compact_locked(self) -> None:
        """Rewrite the journal as one snapshot of the live state."""
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for table, entries in self._state.items():
                for key, value in entries.items():
                    blob = pickle.dumps(("put", table, key, value),
                                        protocol=5)
                    f.write(len(blob).to_bytes(4, "little") + blob)
        self._file.close()
        os.replace(tmp, self.path)
        self._file = open(self.path, "ab")
        self._ops_since_compact = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                pass
