"""Virtual nodes: in-process lightweight cluster members for envelope drills.

Capability parity with the reference's many-hundred-node control plane
tested on one box (reference: the raylet/GCS scale axis —
gcs_node_manager.h node table sized for hundreds of raylets). A
``VirtualNode`` registers with the head over its REAL TCP listener with
the REAL node-daemon handshake (``node_daemon.py`` wire protocol:
AUTH preamble, NODE_REGISTER/REGISTERED, heartbeats, DISPATCH /
TASK_DONE_FWD), so the head sees a genuine ``RemoteNode`` and every
head-side path — scheduler ledger, heartbeat monitor, death reap,
lineage reconstruction, recovery events — is exercised unmodified.

What makes it *virtual* is the daemon side: no process, no worker pool,
no shm arena. All nodes in a :class:`VirtualNodePool` share

* ONE thread pool (``config.virtual_node_executor_threads``) that runs
  dispatched tasks,
* ONE :class:`~ray_tpu.core.object_transfer.ObjectServer` that serves
  every node's store (riding the PR-8 IO loop, zero threads),
* the process-wide IO loop for all sockets and heartbeat timers,

so head-node thread count stays O(1) in node count: 64-128 virtual
nodes cost two sockets each and nothing else. ``tests/
test_cluster_envelope.py`` asserts that envelope; ``devtools/chaos.py``
drives ``kill()`` / ``freeze()`` faults against these nodes.

Intentional infidelities (documented, asserted nowhere):

* task arguments that are not inline resolve through the driver's own
  ``get`` (same process) instead of a worker-side GET_OBJECT round trip;
* streaming tasks (``num_returns=-1``) are rejected;
* a running task cannot be force-killed (threads), only queued ones
  cancel — matching ``CANCEL_TASK`` best-effort semantics.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.protocol import (
    MessageConnection,
    connect_tcp,
    parse_address,
    send_frame,
)
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import ObjectStoreFullError, TaskError

logger = logging.getLogger(__name__)


class VirtualStore:
    """Per-virtual-node object store: plain bytearrays behind a lock.

    Implements both store contracts the transfer layer needs —
    ObjectServer's serve side (``get_buffer``/``release``) and
    ``pull_object``'s destination side (``contains``/``create``/
    ``seal``/``delete``) — plus the packing helpers the node uses for
    task results. Capacity is enforced at ``create`` so drills exercise
    the spill path (``ObjectStoreFullError`` -> spill -> retry).
    """

    def __init__(self, capacity: int):
        self._lock = threading.Lock()
        self._bufs: Dict[ObjectID, bytearray] = {}
        self._sealed: set = set()
        self._capacity = capacity

    # -- raw object ops (pull_object / ObjectServer contract) -----------
    def create(self, object_id: ObjectID, size: int) -> memoryview:
        with self._lock:
            if object_id in self._bufs:
                raise FileExistsError(object_id)
            used = sum(len(b) for b in self._bufs.values())
            if used + size > self._capacity:
                raise ObjectStoreFullError(
                    f"virtual store full: need {size} bytes, "
                    f"{self._capacity - used} free")
            buf = bytearray(size)
            self._bufs[object_id] = buf
        return memoryview(buf)

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            if object_id in self._bufs:
                self._sealed.add(object_id)

    def get_buffer(self, object_id: ObjectID,
                   timeout_s: float = 0.0) -> Optional[memoryview]:
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if object_id in self._sealed:
                    buf = self._bufs.get(object_id)
                    if buf is not None:
                        return memoryview(buf)
                    return None
                absent = object_id not in self._bufs
            # unsealed (concurrent create) or absent: poll within timeout
            if absent or time.monotonic() >= deadline:
                return None
            time.sleep(0.005)

    def release(self, object_id: ObjectID) -> None:
        pass  # bytearrays are GC-owned; no reader pins to drop

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._sealed

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._bufs.pop(object_id, None)
            self._sealed.discard(object_id)

    def used_bytes(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._bufs.values())

    def total_bytes(self) -> int:
        return self._capacity

    def sealed_ids(self) -> List[ObjectID]:
        """Sealed objects, oldest first (dict order) — spill candidates."""
        with self._lock:
            return [oid for oid in self._bufs if oid in self._sealed]

    # -- packing helpers -------------------------------------------------
    def put_packed(self, object_id: ObjectID, packed: bytes) -> int:
        dest = self.create(object_id, len(packed))
        try:
            dest[:] = packed
        finally:
            del dest
        self.seal(object_id)
        return len(packed)

    def get_packed(self, object_id: ObjectID) -> Optional[bytes]:
        with self._lock:
            if object_id in self._sealed:
                buf = self._bufs.get(object_id)
                if buf is not None:
                    return bytes(buf)
        return None


#: the virtual node an executor thread is currently running a task for.
#: Virtual members share the head process, so without this, user code
#: asking "where am I?" would see the head on every member.
_EXEC_CTX = threading.local()


def current_virtual_node_id() -> Optional[NodeID]:
    """NodeID of the virtual node executing on this thread, if any."""
    return getattr(_EXEC_CTX, "node_id", None)


class _ActorCell:
    """One virtual actor: instance + FIFO dispatch queue. Method tasks
    drain in arrival (seq) order on the shared executor — at most one
    drain job per cell is in flight, so ordering holds without a
    dedicated thread."""

    def __init__(self, actor_id: ActorID, instance: Any):
        self.actor_id = actor_id
        self.instance = instance
        self.queue: collections.deque = collections.deque()
        self.running: Dict[TaskID, TaskSpec] = {}
        self.active = False  # drain job submitted / running


class VirtualNode:
    """One virtual cluster member. Created via :class:`VirtualNodePool`.

    ``kill()`` and ``freeze()``/``thaw()`` are the chaos-plane fault
    surface: kill severs the control connection (EOF death at the
    head), freeze withholds heartbeats and delays all other traffic —
    like SIGSTOP on a daemon — until thaw or heartbeat-timeout death.
    """

    def __init__(self, pool: "VirtualNodePool",
                 resources: Dict[str, float], labels: Dict[str, str],
                 store_bytes: int):
        cfg = get_config()
        self.pool = pool
        self.node_id = NodeID.from_random()
        # synthetic stable worker identity for plain (non-actor) tasks
        self.worker_id = WorkerID.from_random()
        self.resources = dict(resources)
        self.labels = dict(labels)
        self.store = VirtualStore(store_bytes)
        self.dead = False
        self._frozen = False
        self._frozen_in: List[bytes] = []   # inbound frames held by freeze
        self._frozen_out: List[dict] = []   # outbound messages held
        self._lock = threading.Lock()
        self._actors: Dict[WorkerID, _ActorCell] = {}
        self._pending: Dict[TaskID, tuple] = {}  # tid -> (future, spec)
        self._hb_interval = cfg.heartbeat_interval_s
        self._conn = self._register()
        self.pool._io.call_later(self._hb_interval, self._hb_tick)

    # --- wire -----------------------------------------------------------
    def _register(self):
        cfg = get_config()
        host, port = parse_address(self.pool.head_address)
        conn = MessageConnection(connect_tcp(host, port, timeout=30.0))
        try:
            if cfg.auth_token:
                # plaintext auth frame BEFORE any pickled message
                # (node_daemon._dial does the same)
                send_frame(conn.sock, b"AUTH" + cfg.auth_token.encode("utf-8"))
            from ray_tpu.core.protocol import PROTOCOL_MINOR, PROTOCOL_VERSION
            conn.sock.settimeout(30.0)
            conn.send({
                "kind": "NODE_REGISTER",
                "proto_version": PROTOCOL_VERSION,
                "proto_minor": PROTOCOL_MINOR,
                "node_id": self.node_id.binary(),
                "resources": self.resources,
                "labels": dict(self.labels),
                "object_addr": [self.pool.object_host,
                                self.pool.object_port],
                "address": f"virtual:{os.getpid()}",
                "actors": [],
            })
            reply = conn.recv()
            if reply is None or reply.get("kind") != "REGISTERED":
                reason = (reply or {}).get("reason", "connection closed")
                raise RuntimeError(
                    f"head rejected virtual node registration: {reason}")
            conn.sock.settimeout(None)
        except BaseException:
            conn.close()
            raise
        # Steady state rides the shared IO loop: the raw socket is
        # adopted by the loop (recv() reads exactly one frame, so no
        # handshake bytes are buffered past this point) — zero threads
        # per node from here on.
        return self.pool._io.register(
            conn.sock, self._on_frames, self._on_close,
            label=f"vnode:{self.node_id.hex()[:8]}")

    def _send(self, msg: dict) -> bool:
        if self.dead:
            return False
        if self._frozen:
            with self._lock:
                if self._frozen:
                    self._frozen_out.append(msg)
                    return True
        try:
            self._conn.send(msg)
            return True
        except OSError:
            return False

    def _hb_tick(self) -> None:
        if self.dead:
            return
        if not self._frozen:
            try:
                self._conn.send({"kind": "HEARTBEAT", "idle": 1,
                                 "store_used": self.store.used_bytes()})
            except OSError:
                return  # connection gone; _on_close handles the rest
        self.pool._io.call_later(self._hb_interval, self._hb_tick)

    def _on_close(self, conn) -> None:
        self.dead = True

    def _on_frames(self, conn, frames) -> None:
        for frame in frames:
            if self._frozen:
                with self._lock:
                    if self._frozen:
                        self._frozen_in.append(frame)
                        continue
            self._dispatch_frame(frame)

    def _dispatch_frame(self, frame: bytes) -> None:
        try:
            msg = serialization.loads(frame)
            self._handle(msg)
        except Exception:  # noqa: BLE001 — keep the node link alive
            traceback.print_exc()

    # --- daemon protocol (node_daemon._handle mirror) --------------------
    def _handle(self, msg: dict) -> None:
        kind = msg["kind"]
        if kind == "DISPATCH":
            spec = serialization.loads(msg["spec"])
            with self._lock:
                fut = self.pool._executor.submit(self._run_plain, spec)
                self._pending[spec.task_id] = (fut, spec)
        elif kind == "DISPATCH_ACTOR":
            self._dispatch_actor(WorkerID(msg["worker_id"]),
                                 serialization.loads(msg["spec"]))
        elif kind == "TO_WORKER":
            pass  # vnode tasks resolve objects in-process, never via
            # GET_OBJECT, so there is no worker to route payloads to
        elif kind == "KILL_WORKER":
            self._kill_worker(WorkerID(msg["worker_id"]))
        elif kind == "PRESTART":
            pass  # no worker pool to warm
        elif kind == "DELETE_OBJECT":
            oid = ObjectID(msg["object_id"])
            self.store.delete(oid)
            self.pool.delete_spilled(oid)
        elif kind == "SPILL_OBJECTS":
            self.pool._executor.submit(self._spill, msg)
        elif kind == "CANCEL_TASK":
            self._cancel_task(TaskID(msg["task_id"]))
        elif kind == "STOP":
            self.kill()
        elif kind == "UNSUPPORTED":
            pass  # answer to OUR probe; never re-answered (echo loop)
        elif msg.get("req_id") is not None:
            self._send({"kind": "UNSUPPORTED", "req_id": msg["req_id"],
                        "unsupported_kind": kind})

    def _dispatch_actor(self, worker_id: WorkerID, spec: TaskSpec) -> None:
        with self._lock:
            cell = self._actors.get(worker_id)
            if cell is not None:
                cell.queue.append(spec)
                if not cell.active:
                    cell.active = True
                    self.pool._executor.submit(self._drain_actor,
                                               worker_id, cell)
                return
        self._send({"kind": "ACTOR_DISPATCH_FAILED",
                    "spec": serialization.dumps_fast(spec)})

    def _kill_worker(self, worker_id: WorkerID) -> None:
        with self._lock:
            cell = self._actors.pop(worker_id, None)
            if cell is None:
                return
            running = list(cell.running.values())
            cell.queue.clear()
        self._send({"kind": "WORKER_CRASHED_FWD",
                    "worker_id": worker_id.binary(),
                    "running": [serialization.dumps_fast(s)
                                for s in running],
                    "actor_id": cell.actor_id.binary()})

    def _cancel_task(self, task_id: TaskID) -> None:
        with self._lock:
            entry = self._pending.get(task_id)
        if entry is None:
            return
        fut, spec = entry
        if fut.cancel():
            with self._lock:
                self._pending.pop(task_id, None)
            self._send({"kind": "TASK_CANCELLED_FWD",
                        "spec": serialization.dumps_fast(spec)})
        # else: already running — threads can't be force-killed; the
        # head's force path falls back to node-level recovery

    def _spill(self, msg: dict) -> None:
        from ray_tpu.core.object_store import spill_objects
        needed = int(msg.get("bytes", 0)) or 1
        wanted = [ObjectID(b) for b in msg.get("object_ids", ())]
        results = spill_objects(self.store, self.pool.spill_dir,
                                wanted or self.store.sealed_ids(), needed)
        self._send({"kind": "SPILLED",
                    "results": [(oid.binary(), path, size)
                                for oid, path, size in results],
                    "freed": sum(size for _, _, size in results),
                    "reply_worker": msg.get("reply_worker"),
                    "req_id": msg.get("req_id")})

    # --- task execution (worker._execute mirror) -------------------------
    def _run_plain(self, spec: TaskSpec) -> None:
        with self._lock:
            self._pending.pop(spec.task_id, None)
        self._run_task(spec, self.worker_id)

    def _drain_actor(self, worker_id: WorkerID, cell: _ActorCell) -> None:
        while True:
            with self._lock:
                if self._actors.get(worker_id) is not cell or not cell.queue:
                    cell.active = False
                    return
                spec = cell.queue.popleft()
                cell.running[spec.task_id] = spec
            try:
                self._run_task(spec, worker_id, cell=cell)
            finally:
                with self._lock:
                    cell.running.pop(spec.task_id, None)

    def _run_task(self, spec: TaskSpec, worker_id: WorkerID,
                  cell: Optional[_ActorCell] = None) -> None:
        # the shared executor thread impersonates this member for the
        # duration of the call, so user code introspecting its placement
        # (get_runtime_context().get_node_id()) sees the virtual node
        _EXEC_CTX.node_id = self.node_id
        try:
            self._run_task_on_node(spec, worker_id, cell)
        finally:
            _EXEC_CTX.node_id = None

    def _run_task_on_node(self, spec: TaskSpec, worker_id: WorkerID,
                          cell: Optional[_ActorCell] = None) -> None:
        if self.dead:
            return
        reply: dict = {"kind": "TASK_DONE",
                       "task_id": spec.task_id.binary(),
                       "spec_is_actor_creation": spec.is_actor_creation,
                       "t_start": time.time()}
        try:
            args, kwargs = self._resolve_args(spec)
            if spec.is_actor_creation:
                cls = self.pool.get_function(spec.function_id)
                instance = cls(*args, **kwargs)
                new_wid = WorkerID.from_random()
                with self._lock:
                    self._actors[new_wid] = _ActorCell(spec.actor_id,
                                                       instance)
                worker_id = new_wid
                result_values = [None]
            else:
                if spec.num_returns == -1:
                    raise RuntimeError(
                        "virtual nodes do not support streaming tasks "
                        "(num_returns=-1); run them on a real node")
                result = self._call_target(spec, cell, args, kwargs)
                result_values = _split_returns(result, spec.num_returns)
            results = []
            for oid, value in zip(spec.return_ids(), result_values):
                results.append(self._pack_result(oid, value))
            reply["results"] = results
            reply["error"] = None
        except Exception:  # noqa: BLE001 — user code may raise anything
            tb = traceback.format_exc()
            import sys
            exc = sys.exc_info()[1]
            try:
                blob = serialization.dumps(
                    TaskError(spec.name or spec.function_id, tb, exc))
            except Exception:  # noqa: BLE001 — unpicklable user exception
                blob = serialization.dumps(
                    TaskError(spec.name or spec.function_id, tb, None))
            reply["results"] = []
            reply["error"] = blob
            reply["error_str"] = tb
        reply["t_end"] = time.time()
        self._send({"kind": "TASK_DONE_FWD",
                    "worker_id": worker_id.binary(),
                    "spec": serialization.dumps_fast(spec),
                    "msg": reply})

    def _call_target(self, spec: TaskSpec, cell: Optional[_ActorCell],
                     args, kwargs) -> Any:
        if cell is not None and spec.actor_id is not None:
            if spec.method_name == "__ray_call__":
                fn = args[0]
                return fn(cell.instance, *args[1:], **kwargs)
            return getattr(cell.instance, spec.method_name)(*args, **kwargs)
        fn = self.pool.get_function(spec.function_id)
        return fn(*args, **kwargs)

    def _resolve_args(self, spec: TaskSpec):
        args = [self._resolve_arg(a) for a in spec.args]
        kwargs = {k: self._resolve_arg(a) for k, a in spec.kwargs.items()}
        return args, kwargs

    def _resolve_arg(self, arg) -> Any:
        if arg.value_bytes is not None:
            return serialization.unpack(arg.value_bytes)
        oid = arg.object_id
        packed = self.store.get_packed(oid)
        if packed is not None:
            return serialization.unpack(packed)
        # Same process as the driver: resolve through the owner directly
        # (pulls/reconstruction included) instead of a GET_OBJECT round
        # trip a real worker would make.
        return self.pool.driver_get(oid)

    def _pack_result(self, oid: ObjectID, value: Any) -> tuple:
        with serialization.collect_contained_refs() as contained:
            data, buffers = serialization.serialize(value)
        contained_bin = [o.binary() for o in contained]
        if not buffers and len(data) < get_config().max_inline_object_size:
            return (oid.binary(), "inline",
                    serialization.pack_parts(data, buffers), contained_bin)
        sizes = [b.nbytes for b in buffers]
        packed_len = serialization.packed_size(data, sizes)
        self._store_with_spill(oid, data, buffers, sizes, packed_len)
        return (oid.binary(), "shm", None, contained_bin)

    def _store_with_spill(self, oid: ObjectID, data, buffers, sizes,
                          packed_len: int) -> None:
        """Pack a result into the store; on pressure, spill the oldest
        sealed objects to disk (reporting SPILLED so the head re-points
        their locations) and retry once."""
        for attempt in (0, 1):
            try:
                dest = self.store.create(oid, packed_len)
                try:
                    serialization.pack_into(dest, data, buffers, sizes)
                finally:
                    del dest
                self.store.seal(oid)
                return
            except ObjectStoreFullError:
                if attempt:
                    raise
                self._spill({"bytes": packed_len})

    # --- chaos fault surface ---------------------------------------------
    def freeze(self) -> None:
        """Suspend the node, SIGSTOP-style: heartbeats stop, inbound and
        outbound control traffic is held (not dropped). The head
        declares the node dead after ``heartbeat_timeout_s``."""
        self._frozen = True

    def thaw(self) -> None:
        """Resume a frozen node, delivering traffic held during the
        freeze (both directions) in order."""
        with self._lock:
            if not self._frozen:
                return
            self._frozen = False
            inbound = self._frozen_in
            outbound = self._frozen_out
            self._frozen_in = []
            self._frozen_out = []
        for msg in outbound:
            if self.dead:
                break
            try:
                self._conn.send(msg)
            except OSError:
                break
        if inbound:
            # inbound frames were captured on the loop thread; replay
            # them there so handler threading invariants hold
            def _replay():
                for frame in inbound:
                    if self.dead:
                        return
                    self._dispatch_frame(frame)
            self.pool._io.call_soon(_replay)

    def kill(self) -> None:
        """Sever the control connection abruptly (process-kill analog).
        The head observes EOF and runs its node-death path."""
        self.dead = True
        try:
            self._conn.close()
        except OSError:
            pass


class VirtualNodePool:
    """Shared substrate for a fleet of virtual nodes: one executor, one
    object server, one spill directory, one function cache. Thread and
    socket cost is O(nodes) sockets but O(1) threads."""

    def __init__(self, head_address: str,
                 spill_dir: Optional[str] = None):
        import tempfile

        from ray_tpu.core.io_loop import get_io_loop
        from ray_tpu.core.object_transfer import ObjectServer
        cfg = get_config()
        self.head_address = head_address
        self._io = get_io_loop()
        self._executor = ThreadPoolExecutor(
            max_workers=cfg.virtual_node_executor_threads,
            thread_name_prefix="vnode-exec")
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="rtpu_vnode_")
        self.nodes: List[VirtualNode] = []
        self._nodes_lock = threading.Lock()
        self._fn_cache: Dict[str, Any] = {}
        self._server = ObjectServer(self._resolve, host=cfg.head_host)
        self.object_host, self.object_port = self._server.address

    # --- node lifecycle --------------------------------------------------
    def start_node(self, resources: Optional[Dict[str, float]] = None,
                   labels: Optional[Dict[str, str]] = None,
                   store_bytes: Optional[int] = None) -> VirtualNode:
        cfg = get_config()
        resources = dict(resources or {})
        resources.setdefault("CPU", 1.0)
        node = VirtualNode(self, resources, dict(labels or {}),
                           store_bytes or cfg.virtual_node_store_bytes)
        with self._nodes_lock:
            self.nodes.append(node)
        return node

    def start_nodes(self, count: int, **kw) -> List[VirtualNode]:
        return [self.start_node(**kw) for _ in range(count)]

    def node_by_id(self, node_id: NodeID) -> Optional[VirtualNode]:
        with self._nodes_lock:
            for node in self.nodes:
                if node.node_id == node_id:
                    return node
        return None

    def live_nodes(self) -> List[VirtualNode]:
        with self._nodes_lock:
            return [n for n in self.nodes if not n.dead]

    def shutdown(self) -> None:
        with self._nodes_lock:
            nodes = list(self.nodes)
            self.nodes.clear()
        for node in nodes:
            node.kill()
        self._executor.shutdown(wait=False)
        self._server.stop()

    # --- shared services -------------------------------------------------
    def _resolve(self, oid: ObjectID):
        """ObjectServer callback: find any node's store (or a spill
        file) holding ``oid`` — one server fronts the whole pool."""
        with self._nodes_lock:
            nodes = list(self.nodes)
        for node in nodes:
            # a killed member's memory died with it — serving its store
            # would let fetches dodge lineage reconstruction. (A frozen
            # member still serves: SIGSTOP keeps host memory intact.)
            if not node.dead and node.store.contains(oid):
                return node.store
        path = os.path.join(self.spill_dir, oid.hex())
        if os.path.exists(path):
            return ("file", path)
        return None

    def delete_spilled(self, oid: ObjectID) -> None:
        path = os.path.join(self.spill_dir, oid.hex())
        if os.path.exists(path):
            try:
                os.unlink(path)
            except OSError:
                pass

    def get_function(self, function_id: str):
        fn = self._fn_cache.get(function_id)
        if fn is None:
            from ray_tpu.core import runtime as runtime_mod
            rt = runtime_mod.get_runtime()
            blob = rt.gcs.get_function(function_id)
            if blob is None:
                raise RuntimeError(
                    f"function {function_id} not found in GCS")
            fn = serialization.loads(blob)
            # benign race: concurrent misses deserialize the same blob
            self._fn_cache[function_id] = fn  # graftlint: disable=GL001
        return fn

    def driver_get(self, oid: ObjectID, timeout: float = 60.0) -> Any:
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.object_ref import ObjectRef
        rt = runtime_mod.get_runtime()
        return rt.get(ObjectRef(oid), timeout=timeout)


def _split_returns(result: Any, num_returns: int) -> List[Any]:
    if num_returns == 1:
        return [result]
    result = list(result)
    if len(result) != num_returns:
        raise ValueError(
            f"task declared num_returns={num_returns} but returned "
            f"{len(result)} values")
    return result
