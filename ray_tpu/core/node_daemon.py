"""Node daemon: runs one Node (worker pool + shm store) on another host.

Capability parity with the reference's per-node raylet process
(reference: src/ray/raylet/main.cc:180 — a raylet per node registering
with the GCS over the network, heartbeating, and executing leased work).
``python -m ray_tpu.core.node_daemon --address HEAD_HOST:PORT`` (or the
``ray-tpu start`` CLI) connects to the head's HeadServer
(ray_tpu/core/remote_node.py), registers the node's resources, and then
serves dispatches. The local ``Node`` is exactly the in-process Node the
head uses — only its ``runtime`` is a ``HeadProxy`` that forwards every
runtime call over the TCP control connection instead of calling the
DriverRuntime directly.

Object data does not transit the control connection: each daemon runs an
ObjectServer (object_transfer.py) and pulls objects it needs directly
from the holder node in bounded chunks.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
from typing import Optional

from ray_tpu.core import serialization
from ray_tpu.core.config import get_config, reset_config
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_transfer import ObjectServer
from ray_tpu.core.protocol import (
    MessageConnection,
    connect_tcp,
    parse_address,
)
from ray_tpu.exceptions import ObjectLostError


class _RefForwarder:
    """Forwards borrowed-ref transitions to the head's ReferenceCounter."""

    def __init__(self, proxy: "HeadProxy"):
        self._proxy = proxy

    def add_local_reference(self, object_id: ObjectID) -> None:
        self._proxy.send({"kind": "REF_ADD",
                          "object_id": object_id.binary()})

    def remove_local_reference(self, object_id: ObjectID) -> None:
        self._proxy.send({"kind": "REF_DROP",
                          "object_id": object_id.binary(), "defer": False})


class HeadProxy:
    """The runtime interface a Node invokes, forwarded to the head."""

    is_driver = False

    def __init__(self, conn: MessageConnection):
        self.conn = conn
        self.dead = threading.Event()
        self.reference_counter = _RefForwarder(self)

    def send(self, msg: dict) -> bool:
        if self.dead.is_set():
            return False
        try:
            self.conn.send(msg)
            return True
        except OSError:
            self.dead.set()
            return False

    # --- runtime interface used by Node --------------------------------
    def submit_spec(self, spec) -> None:
        self.send({"kind": "SUBMIT", "spec": serialization.dumps_fast(spec)})

    def on_worker_put(self, node, msg: dict) -> None:
        self.send({"kind": "PUT_META", "object_id": msg["object_id"],
                   "contained": list(msg.get("contained", ()))})

    def on_stream_item(self, node, msg: dict) -> None:
        self.send({"kind": "STREAM_ITEM", "task_id": msg["task_id"],
                   "object_id": msg["object_id"], "index": msg["index"],
                   "item_kind": msg["item_kind"], "data": msg["data"],
                   "contained": list(msg.get("contained", ()))})

    def handle_stream_next(self, handle, msg: dict) -> None:
        self.send({"kind": "STREAM_NEXT",
                   "worker_id": handle.worker_id.binary(),
                   "task_id": msg["task_id"], "index": msg["index"],
                   "req_id": msg.get("req_id")})

    def handle_get_object(self, node, handle, msg: dict) -> None:
        self.send({"kind": "GET_OBJECT",
                   "worker_id": handle.worker_id.binary(),
                   "object_id": msg["object_id"],
                   "req_id": msg.get("req_id")})

    def handle_check_ready(self, handle, msg: dict) -> None:
        self.send({"kind": "CHECK_READY",
                   "worker_id": handle.worker_id.binary(),
                   "object_ids": msg["object_ids"],
                   "req_id": msg.get("req_id")})

    def handle_subscribe(self, node, handle, msg: dict) -> None:
        self.send({"kind": "SUBSCRIBE",
                   "worker_id": handle.worker_id.binary(),
                   "channel": msg["channel"]})

    def handle_spill_request(self, node, handle, msg: dict) -> None:
        self.send({"kind": "SPILL_REQUEST",
                   "worker_id": handle.worker_id.binary(),
                   "bytes": msg.get("bytes", 0),
                   "req_id": msg.get("req_id")})

    def handle_gcs_request(self, handle, msg: dict) -> None:
        self.send({"kind": "GCS_REQUEST",
                   "worker_id": handle.worker_id.binary(),
                   "method": msg["method"], "args": msg["args"],
                   "req_id": msg.get("req_id")})

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.send({"kind": "KILL_ACTOR", "actor_id": actor_id.binary(),
                   "no_restart": no_restart})

    def cancel(self, object_id: ObjectID, force: bool = False) -> None:
        self.send({"kind": "CANCEL", "object_id": object_id.binary(),
                   "force": force})

    def deferred_remove_reference(self, object_id: ObjectID) -> None:
        self.send({"kind": "REF_DROP", "object_id": object_id.binary(),
                   "defer": True})

    def on_task_done(self, node, worker, spec, msg: dict) -> None:
        self.send({"kind": "TASK_DONE_FWD",
                   "worker_id": worker.worker_id.binary(),
                   "spec": serialization.dumps_fast(spec), "msg": msg})

    def on_worker_crashed(self, node, worker, running, actor_id) -> None:
        self.send({"kind": "WORKER_CRASHED_FWD",
                   "worker_id": worker.worker_id.binary(),
                   "running": [serialization.dumps_fast(s) for s in running],
                   "actor_id": actor_id.binary() if actor_id else None})


class NodeDaemon:
    def __init__(self, head_address: str,
                 resources: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 object_store_memory: Optional[int] = None,
                 session_dir: Optional[str] = None,
                 advertise_host: Optional[str] = None):
        from ray_tpu.core.node import Node  # late: spawns worker procs

        self.head_address = head_address
        self.node_id = NodeID.from_random()
        self._stop_requested = False
        if resources is None:
            resources = {}
        resources = dict(resources)
        if "CPU" not in resources:
            import multiprocessing
            resources["CPU"] = float(multiprocessing.cpu_count())
        labels = dict(labels or {})
        from ray_tpu.accelerators.tpu import TpuAcceleratorManager
        TpuAcceleratorManager.augment_node(resources, labels)
        self.resources = resources
        self.node_labels = dict(labels)
        self._advertise = advertise_host or get_config().head_host
        # must be set BEFORE the Node prestarts workers: they inherit
        # it for cross-host endpoints they advertise (e.g.
        # compiled-graph TCP channel listeners)
        os.environ["RTPU_NODE_ADVERTISE_HOST"] = self._advertise

        self.conn = self._dial()
        self.proxy = HeadProxy(self.conn)
        self.node = Node(self.proxy, self.node_id, resources, labels,
                         object_store_memory=object_store_memory,
                         session_dir=session_dir)
        self.object_server = ObjectServer(self._resolve_store,
                                          host=self._advertise)
        self._adopt(self.conn, self._register_on(self.conn))

    def _dial(self) -> MessageConnection:
        """Dial the head and send the AUTH preamble (registration is a
        separate step — its NODE_REGISTER carries the object-server
        port, which only exists after the ObjectServer starts)."""
        host, port = parse_address(self.head_address)
        conn = MessageConnection(connect_tcp(host, port, timeout=30.0))
        token = get_config().auth_token
        if token:
            # plaintext auth frame BEFORE any pickled message (the head
            # refuses to unpickle from unauthenticated peers)
            from ray_tpu.core.protocol import send_frame
            send_frame(conn.sock, b"AUTH" + token.encode("utf-8"))
        return conn

    def _register_on(self, conn: MessageConnection,
                     timeout_s: float = 30.0) -> dict:
        """NODE_REGISTER/REGISTERED exchange on ``conn`` — bounded, and
        touching NO daemon state (the live connection stays untouched
        until the new one is fully registered)."""
        from ray_tpu.core.protocol import PROTOCOL_MINOR, PROTOCOL_VERSION
        conn.sock.settimeout(timeout_s)
        try:
            conn.send({
                "kind": "NODE_REGISTER",
                "proto_version": PROTOCOL_VERSION,
                "proto_minor": PROTOCOL_MINOR,
                "node_id": self.node_id.binary(),
                "resources": self.resources,
                "labels": dict(self.node_labels),
                "object_addr": [self._advertise,
                                self.object_server.address[1]],
                "address": f"{socket.gethostname()}:{os.getpid()}",
                # live actor workers, so a restarted head re-binds
                # surviving detached/named actors (head FT slice 2)
                "actors": self.node.live_actors(),
            })
            reply = conn.recv()
        finally:
            try:
                conn.sock.settimeout(None)
            except OSError:
                pass
        if reply is None or reply.get("kind") != "REGISTERED":
            reason = (reply or {}).get("reason", "connection closed")
            raise RuntimeError(f"head rejected node registration: {reason}")
        return reply

    def _adopt(self, conn: MessageConnection, reply: dict) -> None:
        """Switch the daemon onto a REGISTERED connection. Ordering
        matters: proxy.dead stays SET until the swap is complete, so
        worker completions can't write frames ahead of registration
        and poison the handshake."""
        self.conn = conn
        self.proxy.conn = conn
        # Negotiated head features (additive minors; protocol.py policy)
        self.head_proto_minor = reply.get("proto_minor", 0)
        self.head_capabilities = frozenset(reply.get("capabilities", ()))
        self.proxy.dead.clear()
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, args=(conn,),
            name="heartbeat", daemon=True)
        self._heartbeat_thread.start()

    def _try_reconnect(self) -> bool:
        """Head link lost: retry within node_reconnect_s, re-registering
        under the SAME node id so a restarted head (journal-replayed
        control plane) adopts this node (reference: raylets reconnecting
        to a restarted GCS, gcs_init_data.cc). Work dispatched before
        the outage is lost — the new head never owned it — and any late
        completions are dropped by the head as unknown tasks. The dead
        flag stays set for the whole attempt, so nothing else writes to
        the half-established connection."""
        from ray_tpu.util.backoff import Backoff

        window = get_config().node_reconnect_s
        if window <= 0 or self._stop_requested:
            return False
        # Jittered (util/backoff.py): after a head restart EVERY daemon
        # in the fleet redials at once, and identical timers would slam
        # the fresh listener in synchronized waves.
        backoff = Backoff(initial_s=0.5, max_s=3.0, deadline_s=window)
        old = self.conn
        while not self._stop_requested:
            if backoff.expired():
                return False
            remaining = backoff.remaining() or 0.0
            try:
                conn = self._dial()
            except OSError:
                if not backoff.wait():
                    return False
                continue
            try:
                reply = self._register_on(conn,
                                          timeout_s=min(15.0, remaining))
            except (RuntimeError, OSError):
                conn.close()  # every failed attempt frees its socket
                if not backoff.wait():
                    return False
                continue
            self._adopt(conn, reply)
            try:
                old.close()
            except OSError:
                pass
            return True
        return False

    def _resolve_store(self, oid: ObjectID):
        if self.node.store.contains(oid):
            return self.node.store
        path = os.path.join(self._spill_dir(), oid.hex())
        if os.path.exists(path):
            return ("file", path)  # spilled: serve straight off disk
        return None

    def _heartbeat_loop(self, conn) -> None:
        cfg = get_config()
        while not self.proxy.dead.wait(cfg.heartbeat_interval_s):
            if self.proxy.conn is not conn:
                return  # superseded: a reconnect started a fresh thread
            self.proxy.send({"kind": "HEARTBEAT",
                             "idle": self.node.idle_worker_count(),
                             "store_used": self.node.store.used_bytes()})

    # --- main loop ------------------------------------------------------
    def serve_forever(self) -> None:
        try:
            while True:
                msg = self.conn.recv()
                if msg is None:
                    # head link lost: survive a head restart when the
                    # reconnect window allows (node_reconnect_s)
                    self.proxy.dead.set()
                    if self._try_reconnect():
                        continue
                    break
                try:
                    if not self._handle(msg):
                        self._stop_requested = True
                        break
                except Exception:  # noqa: BLE001 — keep serving
                    import traceback
                    traceback.print_exc()
        finally:
            self.proxy.dead.set()
            self.shutdown()

    def _handle(self, msg: dict) -> bool:
        kind = msg["kind"]
        if kind == "DISPATCH":
            self.node.dispatch(serialization.loads(msg["spec"]))
        elif kind == "DISPATCH_ACTOR":
            spec = serialization.loads(msg["spec"])
            if not self.node.dispatch_to_actor(WorkerID(msg["worker_id"]),
                                               spec):
                self.proxy.send({"kind": "ACTOR_DISPATCH_FAILED",
                                 "spec": serialization.dumps_fast(spec)})
        elif kind == "TO_WORKER":
            self._route_to_worker(WorkerID(msg["worker_id"]), msg["payload"])
        elif kind == "KILL_WORKER":
            self.node.kill_worker(WorkerID(msg["worker_id"]))
        elif kind == "PRESTART":
            self.node.prestart_workers(msg.get("count", 1),
                                       msg.get("profile", "cpu"))
        elif kind == "DELETE_OBJECT":
            oid = ObjectID(msg["object_id"])
            self.node.store.delete(oid)
            spill_path = os.path.join(self._spill_dir(), oid.hex())
            if os.path.exists(spill_path):
                try:
                    os.unlink(spill_path)
                except OSError:
                    pass
        elif kind == "SPILL_OBJECTS":
            self._spill_objects(msg)
        elif kind == "CANCEL_TASK":
            self._cancel_task(TaskID(msg["task_id"]),
                              force=msg.get("force", True))
        elif kind == "STOP":
            return False
        elif kind == "UNSUPPORTED":
            pass  # answer to OUR probe; never re-answered (echo loop)
        else:
            # Additive evolution (protocol.py policy): answer probes for
            # kinds this daemon predates so a newer head can fall back.
            if msg.get("req_id") is not None:
                self.proxy.send({"kind": "UNSUPPORTED",
                                 "req_id": msg["req_id"],
                                 "unsupported_kind": kind})
        return True

    def _route_to_worker(self, worker_id: WorkerID, payload: dict) -> None:
        if payload.get("status") == "pull":
            # The head pointed us at the holder node; pull the object
            # into the local arena (chunked, node-to-node), then tell the
            # worker it is local (reference: PullManager-driven transfer,
            # pull_manager.h:50).
            threading.Thread(
                target=self._pull_and_reply,
                args=(worker_id, payload), daemon=True).start()
            return
        self._send_to_worker(worker_id, payload)

    def _pull_and_reply(self, worker_id: WorkerID, payload: dict) -> None:
        oid = ObjectID(payload["object_id"])
        addr = tuple(payload["addr"])
        out = {"kind": "OBJECT_VALUE", "req_id": payload.get("req_id")}
        from ray_tpu.core.object_transfer import (
            PRIORITY_TASK_ARG, get_pull_manager)
        if get_pull_manager().pull(addr, oid, self.node.store,
                                   priority=PRIORITY_TASK_ARG):
            self.proxy.send({"kind": "REPLICA", "object_id": oid.binary()})
            out["status"] = "shm_local"
        else:
            out["status"] = "error"
            out["error"] = serialization.dumps(ObjectLostError(oid))
        self._send_to_worker(worker_id, out)

    def _send_to_worker(self, worker_id: WorkerID, payload: dict) -> None:
        with self.node._lock:
            worker = self.node._workers.get(worker_id)
        if worker is not None:
            worker.send(payload)

    def _spill_dir(self) -> str:
        path = os.path.join(self.node.session_dir, "spill")
        os.makedirs(path, exist_ok=True)
        return path

    def _spill_objects(self, msg: dict) -> None:
        """Spill candidates from the local arena until `bytes` are freed
        (reference: LocalObjectManager::SpillObjects). Reports results
        so the head records locations and unblocks the worker."""
        from ray_tpu.core.object_store import spill_objects
        needed = int(msg.get("bytes", 0)) or 1
        results = spill_objects(
            self.node.store, self._spill_dir(),
            [ObjectID(b) for b in msg.get("object_ids", ())], needed)
        self.proxy.send({"kind": "SPILLED",
                         "results": [(oid.binary(), path, size)
                                     for oid, path, size in results],
                         "freed": sum(size for _, _, size in results),
                         "reply_worker": msg.get("reply_worker"),
                         "req_id": msg.get("req_id")})

    def _cancel_task(self, task_id: TaskID, force: bool = True) -> None:
        # node-queued (not yet running): drop + report so the head can
        # fail the ref immediately (queued-task cancel semantics)
        spec = self.node.cancel_queued(task_id)
        if spec is not None:
            self.proxy.send({"kind": "TASK_CANCELLED_FWD",
                             "spec": serialization.dumps_fast(spec)})
            return
        if not force:
            return
        with self.node._lock:
            target = None
            for worker in self.node._workers.values():
                if task_id in worker.running:
                    target = worker.worker_id
                    break
        if target is not None:
            self.node.kill_worker(target)

    def shutdown(self) -> None:
        self.object_server.stop()
        self.node.stop()
        try:
            self.conn.close()
        except OSError:
            pass


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="ray_tpu node daemon (joins a head over TCP)")
    parser.add_argument("--address", required=True,
                        help="head address, host:port")
    parser.add_argument("--resources", default="{}",
                        help="JSON resource dict, e.g. '{\"CPU\": 4}'")
    parser.add_argument("--labels", default="{}",
                        help="JSON node labels")
    parser.add_argument("--object-store-memory", type=int, default=None)
    parser.add_argument("--system-config", default=None,
                        help="JSON system config matching the head's")
    parser.add_argument("--session-dir", default=None)
    args = parser.parse_args(argv)
    if args.system_config:
        reset_config(json.loads(args.system_config))
    daemon = NodeDaemon(
        args.address,
        resources=json.loads(args.resources) or None,
        labels=json.loads(args.labels) or None,
        object_store_memory=args.object_store_memory,
        session_dir=args.session_dir)
    daemon.serve_forever()


if __name__ == "__main__":
    main()
