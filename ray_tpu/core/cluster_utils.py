"""Multi-node cluster simulation on one machine.

Capability parity with the reference's test cluster
(reference: python/ray/cluster_utils.py:135 Cluster — multiple
raylet+store Nodes as local entities sharing one GCS, with declarative
resources, so a dev box can fake a heterogeneous cluster, e.g. TPU pod
topology: ``cluster.add_node(resources={"TPU": 4},
labels={"tpu-pod-type": "v5p-32", "tpu-worker-id": "0"})``).

SURVEY.md §4.2 calls this the single most important piece of test
infrastructure to replicate.
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.ids import NodeID
from ray_tpu.core.runtime import DriverRuntime


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 system_config: Optional[dict] = None):
        head_node_args = dict(head_node_args or {})
        self.runtime = DriverRuntime(
            resources=head_node_args.get("resources"),
            labels=head_node_args.get("labels"),
            object_store_memory=head_node_args.get("object_store_memory"),
            system_config=system_config)
        runtime_mod.set_runtime(self.runtime)
        self.head_node_id = self.runtime.head_node_id
        self.virtual_pool = None  # created on first add_virtual_nodes()

    def add_node(self, num_cpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None) -> NodeID:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        return self.runtime.add_node(res or None, labels, object_store_memory)

    def add_remote_node(self, num_cpus: Optional[float] = None,
                        resources: Optional[Dict[str, float]] = None,
                        labels: Optional[Dict[str, str]] = None,
                        object_store_memory: Optional[int] = None,
                        timeout: float = 30.0):
        """Start a node daemon as a SEPARATE OS PROCESS that joins this
        head over TCP — the real multi-host path (reference: raylet
        processes joining the GCS, src/ray/raylet/main.cc:180). Requires
        the head to have been created with ``head_port >= 0``. Returns
        (NodeID, subprocess.Popen); kill the process to simulate host
        failure."""
        import json
        import subprocess
        import sys
        import time

        if self.runtime.head_address is None:
            raise RuntimeError(
                "head has no TCP listener; pass head_port=0 via "
                "system_config/head_node_args")
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        before = set(self.runtime.nodes)
        cmd = [sys.executable, "-m", "ray_tpu.core.node_daemon",
               "--address", self.runtime.head_address,
               "--resources", json.dumps(res),
               "--labels", json.dumps(labels or {})]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        import os
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        from ray_tpu.core.config import get_config
        if get_config().auth_token:
            # a token set via system_config (not env) must still reach
            # the daemon, or every join is rejected
            env["RTPU_AUTH_TOKEN"] = get_config().auth_token
        proc = subprocess.Popen(cmd, env=env)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            new = set(self.runtime.nodes) - before
            if new:
                return new.pop(), proc
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node daemon exited rc={proc.returncode} before "
                    "registering")
            time.sleep(0.05)
        proc.kill()
        raise TimeoutError("node daemon did not register in time")

    def add_virtual_nodes(self, count: int,
                          resources: Optional[Dict[str, float]] = None,
                          labels: Optional[Dict[str, str]] = None,
                          store_bytes: Optional[int] = None,
                          timeout: float = 60.0):
        """Spin up ``count`` virtual nodes (core/virtual_node.py):
        in-process cluster members that register over the head's real
        TCP listener but share one thread pool and one object server,
        so 64-128 of them fit on one box with O(1) extra threads —
        the chaos-plane envelope substrate. Requires ``head_port >= 0``.
        Returns the list of VirtualNode handles (``.node_id``,
        ``.kill()``, ``.freeze()``/``.thaw()``)."""
        import time

        if self.runtime.head_address is None:
            raise RuntimeError(
                "head has no TCP listener; pass head_port=0 via "
                "system_config")
        pool = self.virtual_pool
        if pool is None:
            from ray_tpu.core.virtual_node import VirtualNodePool
            pool = VirtualNodePool(self.runtime.head_address)
            self.virtual_pool = pool
        nodes = pool.start_nodes(count, resources=resources,
                                 labels=labels, store_bytes=store_bytes)
        # registration is synchronous (blocking handshake), but the
        # head installs the node from its IO loop — wait until all ids
        # are visible to the scheduler before handing them out
        deadline = time.monotonic() + timeout
        wanted = {n.node_id for n in nodes}
        while time.monotonic() < deadline:
            if wanted <= set(self.runtime.nodes):
                return nodes
            time.sleep(0.01)
        raise TimeoutError(
            f"{len(wanted - set(self.runtime.nodes))} virtual nodes "
            "did not register in time")

    def remove_node(self, node_id: NodeID) -> None:
        """Kill a node (its workers die; chaos path)."""
        self.runtime.remove_node(node_id)

    def shutdown(self) -> None:
        pool = self.virtual_pool
        if pool is not None:
            self.virtual_pool = None
            pool.shutdown()
        self.runtime.shutdown()
