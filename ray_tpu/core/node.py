"""Node manager: worker pool + local dispatch for one (possibly simulated) node.

Capability parity with the reference's raylet
(reference: src/ray/raylet/node_manager.h:133 NodeManager;
worker_pool.h:280 WorkerPool with prestart and reuse;
local_lease_manager.cc:121 local dispatch). Each Node owns a unix-socket
listener, a pool of worker subprocesses, and the node's shared-memory
object store arena. The cluster test harness
(ray_tpu/core/cluster_utils.py) runs several Nodes in one head process
to simulate a multi-host TPU pod on a dev box — the same pattern as the
reference's Cluster (reference: python/ray/cluster_utils.py:135).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core import task_phase as _task_phase
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.core.protocol import MessageConnection
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.devtools import threadguard

# Worker states
STARTING = "STARTING"
IDLE = "IDLE"
BUSY = "BUSY"
ACTOR = "ACTOR"
DEAD = "DEAD"


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen,
                 profile: str = "cpu"):
        self.worker_id = worker_id
        self.proc = proc
        self.profile = profile  # "cpu" | "tpu:<k>" — see _spawn_worker
        self.chips: List[int] = []  # TPU chips this worker owns
        self.conn: Optional[MessageConnection] = None
        self.state = STARTING
        self.actor_id: Optional[ActorID] = None
        self.running: Dict[TaskID, TaskSpec] = {}
        self.registered = threading.Event()
        # objects this worker holds borrowed refs to (pinned at owner)
        self.held_refs: set = set()
        # outstanding blocking requests (get/wait/stream-next) — a
        # blocked worker doesn't count toward the pool cap, or nested
        # submission would deadlock (reference: workers blocked in
        # ray.get release their CPU resource)
        self.blocked_requests = 0
        self.node: Optional["Node"] = None

    def send(self, msg: dict) -> bool:
        conn = self.conn
        if conn is None or self.state == DEAD:
            return False
        try:
            conn.send(msg)
            return True
        except OSError:
            return False


class Node:
    proto_minor = 0  # in-process nodes share the head's schema

    def __init__(self, runtime, node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None,
                 session_dir: Optional[str] = None):
        cfg = get_config()
        self.runtime = runtime
        self.node_id = node_id
        self.resources = dict(resources)
        self.labels = dict(labels or {})
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="rtpu_")
        self.socket_path = os.path.join(
            self.session_dir, f"node_{node_id.hex()[:8]}.sock")
        self.store_name = f"rtpu_{node_id.hex()[:16]}"
        self.store = SharedMemoryStore(
            self.store_name,
            size=object_store_memory or cfg.object_store_memory,
            create=True)
        self._lock = threading.RLock()
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        # Separate pools per worker profile: "cpu" workers start with the
        # accelerator runtime masked out (fast startup, no chip
        # contention); "tpu:<k>" workers own k specific chips from the
        # node's chip pool, exported via TPU_VISIBLE_CHIPS + bounds env
        # vars ("tpu:0" = fractional request, shares all chips). This is
        # the reference's per-worker accelerator-visibility plumbing
        # (reference: _private/accelerators/tpu.py:283 TPU_VISIBLE_CHIPS)
        # applied at process-pool level.
        from collections import defaultdict
        self._idle: Dict[str, Deque[WorkerHandle]] = defaultdict(deque)
        self._dispatch_queue: Dict[str, Deque[TaskSpec]] = defaultdict(deque)
        # runtime_env_hash → normalized env dict, registered on first
        # dispatch of a spec carrying that env (ray_tpu/runtime_env/)
        self._runtime_envs: Dict[str, dict] = {}
        self._free_chips: List[int] = list(
            range(int(self.resources.get("TPU", 0))))
        self._total_chips = len(self._free_chips)
        # per-profile pool counters (avoid scanning _workers per dispatch)
        self._n_starting: Dict[str, int] = {}
        self._n_live: Dict[str, int] = {}
        self._n_blocked: Dict[str, int] = {}
        self._stopped = threading.Event()
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(128)
        # Worker connections ride the process-wide selector IO loop:
        # thread-per-worker reader loops anti-scale under the GIL (the
        # reference's raylet is similarly a single asio event loop,
        # src/ray/common/asio/), and one shared loop also covers the
        # head/client/object-transfer sockets (io_loop.py).
        from ray_tpu.core.io_loop import get_io_loop
        self._io = get_io_loop()
        self._listener_handle = self._io.register_listener(
            self._listener, self._on_worker_accept,
            label=f"node-{node_id.hex()[:6]}")
        self.prestart_workers(get_config().min_idle_workers)

    # --- worker pool ---------------------------------------------------
    def _allocate_chips(self, count: int) -> Optional[List[int]]:
        """Take `count` chips from the pool (under self._lock); None if
        the pool is short (the caller reclaims idle TPU workers)."""
        if count <= 0:
            return []
        if len(self._free_chips) < count:
            return None
        taken, self._free_chips = (self._free_chips[:count],
                                   self._free_chips[count:])
        return taken

    def _spawn_worker(self, profile: str = "cpu") -> Optional[WorkerHandle]:
        worker_id = WorkerID.from_random()
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        chips: List[int] = []
        image_uri = None
        hw_profile, _, renv_part = profile.partition("|")
        if renv_part:
            renv = self._runtime_envs.get(renv_part[3:])  # strip "re:"
            if renv is not None:
                import json
                env["RTPU_RUNTIME_ENV"] = json.dumps(renv)
                image_uri = renv.get("image_uri")
        if hw_profile == "cpu":
            # Mask the accelerator: no TPU runtime import (which costs
            # seconds per process and can contend for chips), and any jax
            # the user code imports runs on CPU.
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # axon tunnel opt-out
            env["TPU_VISIBLE_CHIPS"] = ""
        else:
            # "tpu:<k>": the worker owns k chips, exported to the TPU
            # runtime via TPU_VISIBLE_CHIPS + bounds vars (reference:
            # tpu.py:283-323). k=0 (fractional TPU request) shares the
            # full host.
            need = (int(hw_profile.split(":", 1)[1])
                    if ":" in hw_profile else 0)
            with self._lock:
                allocated = self._allocate_chips(need)
                victim = None
                if allocated is None:
                    # Reclaim chips hoarded by idle TPU workers (prefer
                    # actual chip holders — killing a chipless tpu:0
                    # worker frees nothing); retry happens when the
                    # death returns chips to the pool.
                    for p, idle in self._idle.items():
                        if (p.startswith("tpu") and idle
                                and idle[0].chips):
                            victim = idle.popleft()
                            break
                    if victim is None:
                        for p, idle in self._idle.items():
                            if p.startswith("tpu") and idle:
                                victim = idle.popleft()
                                break
            if allocated is None:
                if victim is not None:
                    self.kill_worker(victim.worker_id)
                return None
            chips = allocated
            if chips:
                from ray_tpu.accelerators.tpu import TpuAcceleratorManager
                for key, value in TpuAcceleratorManager.visible_chip_env(
                        chips, self._total_chips).items():
                    if value is None:
                        env.pop(key, None)
                    else:
                        env[key] = value
        # Workers write stdout+stderr to a per-worker session log file
        # (reference: workers log under the session dir; log_monitor.py
        # tails and streams to the driver). The dashboard serves these
        # via /api/logs; PYTHONUNBUFFERED so lines appear as printed.
        env["PYTHONUNBUFFERED"] = "1"
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir,
                                f"worker-{worker_id.hex()[:8]}.log")
        env["RTPU_WORKER_LOG"] = log_path  # worker self-rotates at cap
        cmd = [sys.executable, "-m", "ray_tpu.core.worker",
               "--socket", self.socket_path,
               "--node-id", self.node_id.hex(),
               "--worker-id", worker_id.hex(),
               "--store-name", self.store_name]
        if image_uri:
            # Containerized worker (reference: _private/runtime_env/
            # image_uri.py:24 — podman-run with host net/IPC so the
            # unix socket + shm arena pass through; session/cache/src
            # dirs mounted).
            from ray_tpu.runtime_env.container import (
                container_worker_command)
            from ray_tpu.runtime_env.packaging import cache_root
            sock_dir = os.path.dirname(self.socket_path)
            mounts = [f"{self.session_dir}:{self.session_dir}",
                      f"{cache_root()}:{cache_root()}",
                      f"{pkg_parent}:{pkg_parent}:ro"]
            if os.path.commonpath(
                    [sock_dir, self.session_dir]) != self.session_dir:
                mounts.append(f"{sock_dir}:{sock_dir}")
            if chips or hw_profile.startswith("tpu"):
                # TPU device nodes must be mapped explicitly — host
                # net/IPC do not expose /dev (reference: image_uri
                # worker flags for accelerator access).
                import glob as _glob
                devices = _glob.glob("/dev/accel*")
                if os.path.exists("/dev/vfio"):
                    devices.append("/dev/vfio")
            else:
                devices = []
            try:
                cmd = container_worker_command(image_uri, cmd, env,
                                               mounts=mounts,
                                               devices=devices)
            except RuntimeError as exc:
                # No container runtime on this node: launch plain and
                # let the worker surface RuntimeEnvSetupError to the
                # requesting task (same path as pip failures) instead
                # of stranding the spec in the dispatch queue.
                env["RTPU_PIP_ERROR"] = repr(exc)
        # Deliberate GL009 exception: worker spawn is reachable from
        # loop-thread dispatch paths (_pump / _on_worker_death), but
        # deferring it would break the synchronous _n_starting
        # accounting that gates spawn decisions (two queued REGISTERs
        # would both spawn). Popen is one bounded fork+exec; the
        # threadguard stall watchdog flags it if it ever degrades.
        with open(log_path, "ab") as log_file:
            proc = subprocess.Popen(  # graftlint: disable=GL009
                cmd,
                env=env,
                stdout=log_file,
                stderr=subprocess.STDOUT,
            )
        handle = WorkerHandle(worker_id, proc, profile)
        handle.chips = chips
        handle.node = self
        with self._lock:
            self._workers[worker_id] = handle
            self._n_starting[profile] = self._n_starting.get(profile, 0) + 1
            self._n_live[profile] = self._n_live.get(profile, 0) + 1
        self._emit_worker_event("WORKER_STARTED", "DEBUG", worker_id,
                                profile)
        return handle

    def _emit_worker_event(self, kind: str, severity: str, worker_id,
                           message: str, caused_by=None):
        """Worker lifecycle event, driver-side only: on a remote node
        daemon ``self.runtime`` is the HeadProxy (no GCS) — worker
        crashes are forwarded as WORKER_CRASHED_FWD and narrated by the
        head's on_worker_crashed fallback instead."""
        gcs = getattr(self.runtime, "gcs", None)
        if gcs is None:
            return None
        return gcs.add_cluster_event(kind, severity,
                                     node_id=self.node_id,
                                     worker_id=worker_id,
                                     caused_by=caused_by,
                                     message=message)

    def prestart_workers(self, count: int, profile: str = "cpu") -> None:
        """Warm the pool (reference: worker_pool.h prestart)."""
        for _ in range(count):
            self._spawn_worker(profile)

    def _profile_for(self, spec: TaskSpec) -> str:
        amount = 0.0
        for key, value in spec.resources.items():
            if value > 0 and (key == "TPU" or key.startswith("TPU_group")):
                amount = max(amount, value)
        if amount <= 0:
            base = "cpu"
        elif amount < 1:
            base = "tpu:0"  # fractional request: shares the full host
        else:
            import math
            base = f"tpu:{int(math.ceil(amount))}"
        if spec.runtime_env_hash:
            # Workers with a runtime env form their own sub-pool: a
            # default worker must never execute inside someone else's
            # env, nor vice versa (reference: dedicated workers per
            # runtime_env in worker_pool.cc).
            with self._lock:
                self._runtime_envs.setdefault(
                    spec.runtime_env_hash, spec.runtime_env)
            return f"{base}|re:{spec.runtime_env_hash}"
        return base

    @threadguard.loop_only
    def _on_worker_accept(self, sock, _addr) -> None:
        """Runs on the IO loop thread for each worker that dials the
        node's unix socket. ``holder`` threads the WorkerHandle from
        the REGISTER message into later frames and the close hook."""
        holder = [None]

        def on_msg(conn, msg):
            try:
                holder[0] = self._handle_worker_msg(conn, holder[0], msg)
            except Exception:  # noqa: BLE001 — keep the connection alive
                import traceback
                traceback.print_exc()

        def on_close(conn):
            # Post-stop EOFs are expected (workers exiting on SHUTDOWN);
            # don't drive the death path during teardown.
            if holder[0] is not None and not self._stopped.is_set():
                self._on_worker_death(holder[0])

        self._io.register_message_conn(sock, on_msg, on_close,
                                       label="node-worker")

    def _handle_worker_msg(self, conn: MessageConnection,
                           handle: Optional[WorkerHandle],
                           msg: dict) -> Optional[WorkerHandle]:
            kind = msg["kind"]
            if kind == "REGISTER":
                from ray_tpu.core.protocol import PROTOCOL_VERSION
                peer_version = msg.get("proto_version", 0)
                if peer_version != PROTOCOL_VERSION:
                    # version skew (e.g. a stale worker binary): reject
                    # cleanly instead of failing on message shapes later
                    conn.send({"kind": "SHUTDOWN",
                               "reason": f"protocol version mismatch: "
                                         f"head={PROTOCOL_VERSION} "
                                         f"worker={peer_version}"})
                    return handle
                worker_id = WorkerID(msg["worker_id"])
                with self._lock:
                    handle = self._workers.get(worker_id)
                    if handle is None:  # externally started worker
                        handle = WorkerHandle(worker_id, None)
                        handle.node = self
                        self._workers[worker_id] = handle
                        self._n_live[handle.profile] = \
                            self._n_live.get(handle.profile, 0) + 1
                    else:
                        self._n_starting[handle.profile] = max(
                            0, self._n_starting.get(handle.profile, 0) - 1)
                    handle.conn = conn
                    handle.state = IDLE
                    self._idle[handle.profile].append(handle)
                handle.registered.set()
                self._pump()
            elif handle is None:
                # unregistered (or version-rejected) connection: ignore
                # everything but REGISTER — handlers dereference handle
                return handle
            elif kind == "TASK_DONE":
                self._on_task_done(handle, msg)
            elif kind == "TASK_DONE_BATCH":
                self._on_task_batch_done(handle, msg)
            elif kind == "RETURN_SPECS":
                # the worker is blocking: it hands queued specs back for
                # re-dispatch elsewhere
                self._on_specs_returned(handle, msg)
            elif kind == "BLOCKED":
                # the worker reports it is blocking on an object: take
                # it out of the pool-cap accounting so queued work can
                # still spawn replacements (nested submit+get)
                if handle is not None:
                    self._mark_blocked(handle)
            elif kind == "UNBLOCKED":
                if handle is not None:
                    self._mark_unblocked(handle)
            elif kind == "GET_OBJECT":
                self.runtime.handle_get_object(self, handle, msg)
            elif kind == "CHECK_READY":
                self.runtime.handle_check_ready(handle, msg)
            elif kind == "STREAM_NEXT":
                self.runtime.handle_stream_next(handle, msg)
            elif kind == "SUBMIT":
                spec = serialization.loads(msg["spec"])
                self.runtime.submit_spec(spec)
            elif kind == "PUT_META":
                self.runtime.on_worker_put(self, msg)
            elif kind == "STREAM_ITEM":
                self.runtime.on_stream_item(self, msg)
            elif kind == "SUBSCRIBE":
                self.runtime.handle_subscribe(self, handle, msg)
            elif kind == "SPILL_REQUEST":
                self.runtime.handle_spill_request(self, handle, msg)
            elif kind == "GCS_REQUEST":
                self.runtime.handle_gcs_request(handle, msg)
            elif kind == "KILL_ACTOR":
                self.runtime.kill_actor(ActorID(msg["actor_id"]),
                                        no_restart=msg.get("no_restart", True))
            elif kind == "REF_ADD":
                oid = ObjectID(msg["object_id"])
                if handle is not None:
                    handle.held_refs.add(oid)
                self.runtime.reference_counter.add_local_reference(oid)
            elif kind == "REF_DROP":
                oid = ObjectID(msg["object_id"])
                if handle is not None:
                    handle.held_refs.discard(oid)
                self.runtime.deferred_remove_reference(oid)
            elif kind == "CANCEL":
                self.runtime.cancel(ObjectID(msg["object_id"]),
                                    force=msg.get("force", False))
            return handle

    # --- dispatch ------------------------------------------------------
    def _mark_blocked(self, worker: WorkerHandle) -> None:
        spawn = False
        with self._lock:
            if worker.state == ACTOR:
                # actor workers already left the pool count at creation;
                # counting their blocks would drive the cap negative
                return
            worker.blocked_requests += 1
            if worker.blocked_requests == 1:
                self._n_blocked[worker.profile] = \
                    self._n_blocked.get(worker.profile, 0) + 1
                # escape hatch: queued work may now be spawnable
                profile = worker.profile
                spawn = (bool(self._dispatch_queue.get(profile))
                         and self._n_starting.get(profile, 0) == 0
                         and self._effective_live(profile)
                         < self._worker_cap(profile))
        if spawn:
            self._spawn_worker(worker.profile)

    def _mark_unblocked(self, worker: WorkerHandle) -> None:
        with self._lock:
            if worker.blocked_requests > 0:
                worker.blocked_requests -= 1
                if worker.blocked_requests == 0:
                    self._n_blocked[worker.profile] = max(
                        0, self._n_blocked.get(worker.profile, 0) - 1)

    def _effective_live(self, profile: str) -> int:
        """Pool workers counting toward the cap: live minus blocked."""
        return (self._n_live.get(profile, 0)
                - self._n_blocked.get(profile, 0))

    def _worker_cap(self, profile: str) -> int:
        """Max live workers per profile (reference: worker_pool.h
        maximum_startup_concurrency + num_cpus-bounded pool). Without
        this, a deep dispatch queue would fork one process per task.
        TPU pools are bounded by chips, not CPUs — a 1-CPU host with 2
        chips must still run 2 single-chip workers concurrently."""
        cfg = get_config()
        if cfg.max_workers_per_node > 0:
            return cfg.max_workers_per_node
        if profile.startswith("tpu:"):
            k = int(profile.partition("|")[0].split(":", 1)[1])
            if k > 0 and self._total_chips:
                return max(1, self._total_chips // k)
        return max(1, int(self.resources.get("CPU", 1)))

    def dispatch(self, spec: TaskSpec) -> None:
        """Run a (non-actor-method) task on this node. Resources already
        acquired by the cluster scheduler."""
        profile = self._profile_for(spec)
        with self._lock:
            idle = self._idle[profile]
            worker = idle.popleft() if idle else None
            if worker is not None:
                self._send_task(worker, spec)
                return
            # Pipeline: hand a busy-but-shallow worker a second spec so
            # it never idles a round trip (reference: owner-side lease
            # reuse); deeper backlogs park in the profile queue, from
            # which completions refill workers in batches. The scan is
            # restricted to the empty-queue case (light load) so a deep
            # backlog never pays O(workers) per dispatch, and skips
            # actor creations both as payload (they must own a worker)
            # and as hosts (a creating worker is off-limits).
            if (not spec.is_actor_creation
                    and not self._dispatch_queue[profile]
                    and self._effective_live(profile)
                    >= self._worker_cap(profile)):
                for candidate in self._workers.values():
                    if (candidate.profile == profile
                            and candidate.state == BUSY
                            and len(candidate.running) < 2
                            and candidate.blocked_requests == 0
                            and not any(s.is_actor_creation
                                        for s in
                                        candidate.running.values())):
                        self._send_task(candidate, spec)
                        return
            self._dispatch_queue[profile].append(spec)
            n_starting = self._n_starting.get(profile, 0)
            if (n_starting < len(self._dispatch_queue[profile])
                    and self._effective_live(profile)
                    < self._worker_cap(profile)):
                self._spawn_worker(profile)

    def dispatch_to_actor(self, worker_id: WorkerID, spec: TaskSpec) -> bool:
        """Send an actor method task to the actor's dedicated worker; the
        worker's thread pool queues it FIFO (ordering guarantee)."""
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None or worker.state in (DEAD,):
                return False
            worker.running[spec.task_id] = spec
            return worker.send({"kind": "EXECUTE_ACTOR_TASK",
                                "spec": serialization.dumps_fast(spec)})

    def _send_task(self, worker: WorkerHandle, spec: TaskSpec) -> None:
        worker.state = BUSY
        worker.running[spec.task_id] = spec
        kind = "CREATE_ACTOR" if spec.is_actor_creation else "EXECUTE"
        if _task_phase._TRACKED:  # sampled-chain brackets (task_phase.py)
            _task_phase.mark(spec.task_id, "lease-dispatch")
            payload = serialization.dumps_fast(spec)
            _task_phase.mark(spec.task_id, "frame-encode")
            ok = worker.send({"kind": kind, "spec": payload})
            _task_phase.mark(spec.task_id, "wire-write")
        else:
            ok = worker.send({"kind": kind,
                              "spec": serialization.dumps_fast(spec)})
        if not ok:
            # This spec never reached the worker: requeue without
            # consuming a retry, then run the FULL death path so other
            # in-flight (pipelined) specs on this worker are retried too
            # — setting DEAD here would make the later EOF handler
            # early-return and strand them.
            self._dispatch_queue[worker.profile].appendleft(spec)
            del worker.running[spec.task_id]
            self._on_worker_death(worker)
            # The IO thread may not have noticed this death yet, so
            # make sure a replacement exists to drain the queue.
            self._spawn_worker(worker.profile)

    def _pump(self) -> None:
        """Match queued specs with idle workers; spawn for starved TPU
        queues (a finished worker may now be an idle chip holder the
        spawn path can reclaim)."""
        with self._lock:
            profiles = list(self._dispatch_queue.keys())
        for profile in profiles:
            while True:
                with self._lock:
                    queue = self._dispatch_queue[profile]
                    idle = self._idle[profile]
                    if not queue or not idle:
                        break
                    spec = queue.popleft()
                    worker = idle.popleft()
                    self._send_task(worker, spec)
        for profile in profiles:
            with self._lock:
                starved = (
                    self._dispatch_queue[profile]
                    and not self._idle[profile]
                    and self._n_starting.get(profile, 0) == 0
                    and (profile.startswith("tpu")  # chip reclaim path
                         or self._effective_live(profile)
                         < self._worker_cap(profile)))
            if starved:
                self._spawn_worker(profile)

    def _on_task_done(self, worker: WorkerHandle, msg: dict) -> None:
        task_id = TaskID(msg["task_id"])
        batch = None
        spawn_profile = None
        with self._lock:
            spec = worker.running.pop(task_id, None)
            if spec is None:
                return
            if spec.is_actor_creation and msg.get("error") is None:
                worker.state = ACTOR
                worker.actor_id = spec.actor_id
                # Actor workers leave the task pool: the pool cap must
                # not count them or long-lived actors starve task
                # dispatch (serve runs dozens of actors per node).
                self._n_live[worker.profile] = max(
                    0, self._n_live.get(worker.profile, 0) - 1)
                if worker.blocked_requests > 0:
                    # it blocked during __init__: clear the pool-side
                    # mark too, since actor blocks are no longer counted
                    worker.blocked_requests = 0
                    self._n_blocked[worker.profile] = max(
                        0, self._n_blocked.get(worker.profile, 0) - 1)
                # This worker's departure may leave queued specs with no
                # pool worker to drain them.
                if (self._dispatch_queue.get(worker.profile)
                        and self._n_starting.get(worker.profile, 0) == 0
                        and self._n_live.get(worker.profile, 0)
                        < self._worker_cap(worker.profile)):
                    spawn_profile = worker.profile
            elif worker.state == BUSY:
                # Fast path: keep the worker's pipeline topped up
                # straight from its own profile's queue — a full _pump()
                # scan per completion is the throughput bottleneck.
                batch = self._refill_locked(worker)
        if spawn_profile is not None:
            self._spawn_worker(spawn_profile)
        if batch:
            self._send_batch(worker, batch)
        self.runtime.on_task_done(self, worker, spec, msg)

    def _refill_locked(self, worker: WorkerHandle) -> Optional[List[TaskSpec]]:
        """Top up a busy worker's pipeline from its profile queue
        (called under self._lock). Returns the batch to send, or None.
        Batching amortizes the head's per-message cost — the single
        IO thread is the task-throughput ceiling."""
        queue = self._dispatch_queue.get(worker.profile)
        if worker.blocked_requests > 0:
            # the worker would only bounce refills while blocked
            return None
        if queue and len(worker.running) < 32:
            take = min(len(queue), 32 - len(worker.running), 16)
            batch: List[TaskSpec] = []
            while len(batch) < take and queue:
                head = queue[0]
                if head.is_actor_creation:
                    # An actor creation must own a fresh worker: send it
                    # alone once this worker has fully drained.
                    if not worker.running and not batch:
                        batch.append(queue.popleft())
                    break
                if not self._batchable(head):
                    if not batch:
                        batch.append(queue.popleft())  # dispatch singly
                    break
                batch.append(queue.popleft())
            if batch:
                for spec in batch:
                    worker.running[spec.task_id] = spec
                return batch
        if not worker.running:
            worker.state = IDLE
            self._idle[worker.profile].append(worker)
        return None

    @staticmethod
    def _batchable(spec: TaskSpec) -> bool:
        """Batch-mates execute sequentially in one worker slot, so a
        spec whose inline args embed unresolved ObjectRefs (no
        dependency edge — the head never waited for them) could block
        on a batch-mate's output: head-of-line deadlock. Dispatch those
        singly; direct object_id deps are safe (resolved before
        dispatch). Streaming tasks stay single for reply ordering."""
        if spec.num_returns == -1:
            return False
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if (arg.value_bytes is not None
                    and getattr(arg, "_keepalive", None)):
                return False
        return True

    def _send_batch(self, worker: WorkerHandle,
                    batch: List[TaskSpec]) -> None:
        if len(batch) == 1:
            with self._lock:
                if worker.running.pop(batch[0].task_id, None) is None:
                    return  # worker died; the crash path retried it
                self._send_task(worker, batch[0])
            return
        if _task_phase._TRACKED:  # sampled-chain brackets (task_phase.py)
            for spec in batch:
                _task_phase.mark(spec.task_id, "lease-dispatch")
            payload = serialization.dumps_fast(batch)
            for spec in batch:
                _task_phase.mark(spec.task_id, "frame-encode")
            ok = worker.send({"kind": "EXECUTE_BATCH", "specs": payload})
            for spec in batch:
                _task_phase.mark(spec.task_id, "wire-write")
        else:
            ok = worker.send({"kind": "EXECUTE_BATCH",
                              "specs": serialization.dumps_fast(batch)})
        if not ok:
            with self._lock:
                for spec in batch:
                    if worker.running.pop(spec.task_id, None) is not None:
                        self._dispatch_queue[worker.profile].appendleft(spec)
            # full death path: retries any remaining in-flight specs
            self._on_worker_death(worker)
            self._spawn_worker(worker.profile)

    def _on_task_batch_done(self, worker: WorkerHandle, msg: dict) -> None:
        done = []
        batch = None
        with self._lock:
            for item in msg["items"]:
                spec = worker.running.pop(TaskID(item["task_id"]), None)
                if spec is not None:
                    done.append((spec, item))
            if worker.state == BUSY:
                batch = self._refill_locked(worker)
        if batch:
            self._send_batch(worker, batch)
        for spec, item in done:
            self.runtime.on_task_done(self, worker, spec, item)

    def _on_specs_returned(self, worker: WorkerHandle, msg: dict) -> None:
        with self._lock:
            for tid_bytes in msg["task_ids"]:
                spec = worker.running.pop(TaskID(tid_bytes), None)
                if spec is not None:
                    self._dispatch_queue[worker.profile].appendleft(spec)
        self._pump()

    def _on_worker_death(self, worker: WorkerHandle) -> None:
        with self._lock:
            if worker.state == DEAD:
                return
            was_actor = worker.state == ACTOR
            if worker.state == STARTING:
                self._n_starting[worker.profile] = max(
                    0, self._n_starting.get(worker.profile, 0) - 1)
            if not was_actor:  # actor workers already left the pool count
                self._n_live[worker.profile] = max(
                    0, self._n_live.get(worker.profile, 0) - 1)
            if worker.blocked_requests > 0:
                worker.blocked_requests = 0
                self._n_blocked[worker.profile] = max(
                    0, self._n_blocked.get(worker.profile, 0) - 1)
            worker.state = DEAD
            running = list(worker.running.values())
            worker.running.clear()
            held = list(worker.held_refs)
            worker.held_refs.clear()
            try:
                self._idle[worker.profile].remove(worker)
            except ValueError:
                pass
            self._workers.pop(worker.worker_id, None)
            # Return this worker's chips; TPU specs may be queued
            # waiting for exactly these.
            if worker.chips:
                self._free_chips.extend(worker.chips)
                worker.chips = []
            starved = [
                p for p, q in self._dispatch_queue.items()
                if q and p.startswith("tpu") and not self._idle[p]
                and self._n_starting.get(p, 0) == 0
            ]
        for oid in held:  # release this worker's borrowed pins
            self.runtime.reference_counter.remove_local_reference(oid)
        if self._stopped.is_set():
            return
        # Root event for this worker's incident; the seq rides the
        # handle so on_worker_crashed chains retries/actor deaths to
        # it. Idle reclaims (nothing running, no actor) are DEBUG —
        # they root no recovery work.
        severity = "ERROR" if (running or was_actor) else "DEBUG"
        worker._exit_event_seq = self._emit_worker_event(
            "WORKER_EXIT", severity, worker.worker_id,
            f"{len(running)} tasks in flight" if running else "",
            caused_by=getattr(worker, "_chaos_cause_seq", None))
        for profile in starved:
            self._spawn_worker(profile)
        self.runtime.on_worker_crashed(self, worker, running,
                                       worker.actor_id if was_actor else None)

    def cancel_queued(self, task_id: TaskID) -> Optional[TaskSpec]:
        """Remove a not-yet-running spec from this node's dispatch
        queues (burst-granted specs park here); None if the spec
        already reached a worker."""
        with self._lock:
            for queue in self._dispatch_queue.values():
                for spec in queue:
                    if spec.task_id == task_id:
                        queue.remove(spec)
                        return spec
        return None

    def idle_worker_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._idle.values())

    def kill_worker(self, worker_id: WorkerID) -> None:
        with self._lock:
            worker = self._workers.get(worker_id)
        if worker is not None:
            worker.send({"kind": "KILL"})
            if worker.proc is not None:
                try:
                    worker.proc.kill()
                except ProcessLookupError:
                    pass

    def live_actors(self) -> List[Tuple[bytes, bytes]]:
        """(actor_id, worker_id) for every live actor worker — reported
        in NODE_REGISTER so a restarted head re-binds surviving
        detached/named actors (reference: gcs_init_data.cc replaying
        actor ownership on GCS restart)."""
        with self._lock:
            return [(w.actor_id.binary(), w.worker_id.binary())
                    for w in self._workers.values()
                    if w.state == ACTOR and w.actor_id is not None]

    # --- shutdown ------------------------------------------------------
    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            workers = list(self._workers.values())
        for worker in workers:
            worker.send({"kind": "SHUTDOWN"})
        deadline = time.time() + 2.0
        for worker in workers:
            if worker.proc is None:
                continue
            remaining = max(0.05, deadline - time.time())
            try:
                worker.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                worker.proc.kill()
        self._listener_handle.close(wait=True)
        for worker in workers:
            if worker.conn is not None:
                worker.conn.close()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self.store.close()
