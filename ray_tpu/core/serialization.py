"""Serialization of task args, returns, and put objects.

Capability parity with the reference's serialization context
(reference: python/ray/_private/serialization.py:145 plus the cloudpickle
fork in python/ray/cloudpickle/): cloudpickle for closures, pickle
protocol 5 out-of-band buffers so large numpy/Arrow payloads are written
once into the shared-memory store and read back zero-copy.

Wire format of a packed object:
    [u32 pickled_len][u32 index_len][index: pickled list of buffer sizes]
    [pickled bytes][pad][buffer 0][pad][buffer 1]...
with every out-of-band buffer 64-byte aligned so numpy views are aligned
for TPU host staging.
"""

from __future__ import annotations

import io
import pickle
import sys
import threading
from typing import Any, List, Tuple

import cloudpickle

from ray_tpu.devtools import refsan

# Regression-fixture hook (tests only): when set, unpack_pinned takes
# the pre-PR-11 buggy path — on_release fires while zero-copy views of
# the arena are still live — so tier-1 can prove the refsan eviction
# canary re-detects that bug class deterministically.
_FIXTURE_EARLY_RELEASE = False

# --- nested-ref collection -------------------------------------------------
# While a collector is active on this thread, every ObjectRef pickled
# reports its id here. Used to pin objects *contained in* stored values
# (task returns, puts) until the containing object dies — the reference's
# nested-reference counting (reference: reference_counter.h "contained in
# owned object" tracking).
_ref_collector = threading.local()


class collect_contained_refs:
    """Context manager yielding the list of ObjectIDs pickled within."""

    def __enter__(self):
        self._prev = getattr(_ref_collector, "refs", None)
        _ref_collector.refs = []
        return _ref_collector.refs

    def __exit__(self, *exc):
        _ref_collector.refs = self._prev
        return False


def note_ref(object_id) -> None:
    refs = getattr(_ref_collector, "refs", None)
    if refs is not None:
        refs.append(object_id)

ALIGNMENT = 64
# Buffers below this size are serialized in-band; pickle5 callbacks only
# divert buffers worth the indirection.
OOB_THRESHOLD = 4096


def _align(offset: int) -> int:
    return (offset + ALIGNMENT - 1) & ~(ALIGNMENT - 1)


_PLAIN_TYPES = frozenset((int, float, bool, str, bytes, type(None)))
_SEQ_TYPES = frozenset((tuple, list))


def _plain_picklable(value: Any) -> bool:
    """True for values plain pickle serializes IDENTICALLY to
    cloudpickle — primitives, non-object numpy, and small flat
    containers of primitives. Callables/classes must NOT take this
    path: plain pickle serializes __main__ definitions by reference,
    which unpickles to the wrong (or no) object in a worker whose
    __main__ is the worker module."""
    t = type(value)
    if t in _PLAIN_TYPES:
        return True  # before the numpy import: ints/strs need no numpy
    import numpy as np  # module is cached; the name lookup is cheap
    if t is np.ndarray:
        # hasobject also catches structured dtypes with object FIELDS
        # (dtype != object misses those) — any embedded Python object
        # could be a __main__ callable that must go by value
        return not value.dtype.hasobject
    if isinstance(value, np.generic):
        return not value.dtype.hasobject
    if t in _SEQ_TYPES and len(value) <= 32:
        return all(type(v) in _PLAIN_TYPES for v in value)
    if t is dict and len(value) <= 32:
        return all(type(k) in _PLAIN_TYPES and type(v) in _PLAIN_TYPES
                   for k, v in value.items())
    return False


def serialize(value: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (pickled_bytes, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        if buf.raw().nbytes >= OOB_THRESHOLD:
            buffers.append(buf)
            return False  # keep out of band
        return True  # serialize in band

    if _plain_picklable(value):
        # C pickler: ~10-40x cheaper than cloudpickle's Python Pickler
        # (which was a top entry in the actor-call profile). Identical
        # wire semantics for these types, including protocol-5 buffers.
        data = pickle.dumps(value, protocol=5,
                            buffer_callback=buffer_callback)
    else:
        data = cloudpickle.dumps(value, protocol=5,
                                 buffer_callback=buffer_callback)
    return data, [b.raw() for b in buffers]


def pack_parts(data: bytes, buffers: List[memoryview]) -> bytes:
    """Assemble pre-serialized parts into the packed wire format."""
    sizes = [b.nbytes for b in buffers]
    index = pickle.dumps(sizes, protocol=4)
    out = io.BytesIO()
    out.write(len(data).to_bytes(4, "little"))
    out.write(len(index).to_bytes(4, "little"))
    out.write(index)
    out.write(data)
    pos = out.tell()
    for buf in buffers:
        aligned = _align(pos)
        out.write(b"\x00" * (aligned - pos))
        out.write(buf.cast("B") if buf.format != "B" or buf.ndim != 1 else buf)
        pos = aligned + buf.nbytes
    return out.getvalue()


def pack(value: Any) -> bytes:
    """Pack a value into a single self-describing byte string."""
    data, buffers = serialize(value)
    return pack_parts(data, buffers)


def packed_size(data: bytes, sizes: List[int]) -> int:
    index = pickle.dumps(sizes, protocol=4)
    pos = 8 + len(index) + len(data)
    for size in sizes:
        pos = _align(pos) + size
    return pos


def pack_into(dest: memoryview, data: bytes,
              buffers: List[memoryview], sizes: List[int]) -> None:
    """Write pre-serialized parts into a destination buffer (e.g. the
    shared-memory arena) without an intermediate copy."""
    index = pickle.dumps(sizes, protocol=4)
    pos = 0
    dest[pos:pos + 4] = len(data).to_bytes(4, "little"); pos += 4
    dest[pos:pos + 4] = len(index).to_bytes(4, "little"); pos += 4
    dest[pos:pos + len(index)] = index; pos += len(index)
    dest[pos:pos + len(data)] = data; pos += len(data)
    for buf, size in zip(buffers, sizes):
        aligned = _align(pos)
        if aligned != pos:
            dest[pos:aligned] = b"\x00" * (aligned - pos)
        flat = buf.cast("B") if (buf.format != "B" or buf.ndim != 1) else buf
        dest[aligned:aligned + size] = flat
        pos = aligned + size


def unpack(src) -> Any:
    """Unpack from bytes/memoryview; large numpy arrays view ``src`` zero-copy
    (when ``src`` is a memoryview over shared memory)."""
    src = memoryview(src)
    data_len = int.from_bytes(src[0:4], "little")
    index_len = int.from_bytes(src[4:8], "little")
    offset = 8
    sizes = pickle.loads(src[offset : offset + index_len])
    offset += index_len
    data = src[offset : offset + data_len]
    offset += data_len
    buffers = []
    for size in sizes:
        offset = _align(offset)
        buffers.append(src[offset : offset + size])
        offset += size
    return pickle.loads(data, buffers=buffers)


def unpack_pinned(src, on_release) -> Any:
    """Like unpack(), but ties ``on_release`` to the *value's* lifetime.

    Zero-copy deserialization hands out numpy views into the shared
    memory arena; the store pin must outlive those views, not the
    ObjectRef (reference: plasma client buffers stay valid while the
    deserialized value is referenced, store_provider/plasma_store_
    provider.h:94). Each out-of-band buffer is wrapped in a PEP-688
    buffer-provider the arrays keep alive; when the last wrapper is
    collected, ``on_release`` fires. Values with no out-of-band buffers
    are fully copied by pickle, so ``on_release`` fires immediately.
    """
    src = memoryview(src)
    data_len = int.from_bytes(src[0:4], "little")
    index_len = int.from_bytes(src[4:8], "little")
    offset = 8
    sizes = pickle.loads(src[offset : offset + index_len])
    offset += index_len
    data = src[offset : offset + data_len]
    offset += data_len
    if not sizes:
        value = pickle.loads(data)
        on_release()
        return value
    if _FIXTURE_EARLY_RELEASE:
        # Pre-PR-11 bug shape, preserved behind a test-only flag: the
        # pin is released as soon as deserialization returns, while the
        # value still holds zero-copy views into the arena. With the
        # refsan canary on, the next slot free poisons the range and
        # verify_views() flags every one of these views.
        import ctypes
        led = refsan.LEDGER
        buffers = []
        for size in sizes:
            offset = _align(offset)
            ct = (ctypes.c_char * size).from_buffer(src[offset:offset + size])
            if led is not None:
                led.register_view(ct, size)
            buffers.append(ct)
            offset += size
        try:
            value = pickle.loads(data, buffers=buffers)
        finally:
            del buffers
            on_release()  # BUG under test: views outlive the pin
        return value
    if sys.version_info < (3, 12):
        # Python classes can't export the buffer protocol before
        # PEP 688, but ctypes arrays can: hand pickle zero-copy ctypes
        # views of each payload slice. A reconstructed array's .base
        # chain keeps its ctypes view alive, so the store pin (released
        # via the finalizers) outlives the VALUE, not just the
        # ObjectRef — dropping the ref early must not let the arena
        # slot be reused under a live view.
        import ctypes
        import weakref

        remaining = [len(sizes)]

        def _dec():
            remaining[0] -= 1
            if remaining[0] == 0:
                try:
                    on_release()
                except Exception:  # graftlint: disable=GL004
                    pass  # finalizer may run at interpreter shutdown

        led = refsan.LEDGER
        buffers = []
        for size in sizes:
            offset = _align(offset)
            ct = (ctypes.c_char * size).from_buffer(src[offset:offset + size])
            weakref.finalize(ct, _dec)
            if led is not None:
                led.register_view(ct, size)
            buffers.append(ct)
            offset += size
        try:
            return pickle.loads(data, buffers=buffers)
        except BaseException:
            del buffers  # fire on_release via the finalizers
            raise
    remaining = [len(sizes)]

    class _PinnedBuffer:
        """Buffer provider (PEP 688) releasing the store pin at GC."""

        # __weakref__: the refsan view registry tracks these by weakref
        __slots__ = ("_view", "__weakref__")

        def __init__(self, view):
            self._view = view

        def __buffer__(self, flags):
            return memoryview(self._view)

        def __release_buffer__(self, view):
            pass

        def __del__(self):
            remaining[0] -= 1
            if remaining[0] == 0:
                try:
                    on_release()
                except Exception:  # graftlint: disable=GL004
                    pass  # __del__ from GC context

    led = refsan.LEDGER
    buffers = []
    for size in sizes:
        offset = _align(offset)
        pb = _PinnedBuffer(src[offset : offset + size])
        if led is not None:
            led.register_view(pb, size)
        buffers.append(pb)
        offset += size
    try:
        return pickle.loads(data, buffers=buffers)
    except BaseException:
        del buffers  # fire on_release via the wrappers
        raise


def _maybe_register_by_value(value: Any, _depth: int = 0) -> None:
    """Ship user-module code by value.

    Workers can import installed packages but not the driver's ad-hoc
    modules (a pytest file, a script next to the driver). The reference
    ships such code via runtime_env working_dir (reference:
    python/ray/_private/runtime_env/working_dir.py); the single-machine
    equivalent is pickling user-module classes/functions by value.

    Shallow containers are walked (bounded) so a callable tucked inside
    a kwargs dict — the standard actor-init blob shape — ships the same
    way a bare callable does.
    """
    import sys
    import sysconfig

    if _depth < 2 and isinstance(value, (list, tuple, set, frozenset,
                                         dict)):
        items = value.values() if isinstance(value, dict) else value
        for i, v in enumerate(items):
            if i >= 64:
                break
            _maybe_register_by_value(v, _depth + 1)
        if type(value) in (list, tuple, set, frozenset, dict):
            return
        # a user-defined container SUBCLASS still needs its own class
        # shipped by value — fall through to type registration

    target = value if isinstance(value, type) or callable(value) else type(value)
    mod_name = getattr(target, "__module__", None)
    if not mod_name or mod_name == "__main__":
        return  # __main__ is already by-value in cloudpickle
    if mod_name.split(".")[0] in ("ray_tpu", "builtins"):
        return
    mod = sys.modules.get(mod_name)
    mod_file = getattr(mod, "__file__", None) if mod else None
    if not mod_file:
        return
    stdlib = sysconfig.get_paths()["stdlib"]
    if (mod_file.startswith(sys.prefix) or mod_file.startswith(stdlib)
            or "site-packages" in mod_file):
        return
    # Modules workers CAN import (resolvable from cwd, where workers
    # start) stay by-reference so class identity survives the round
    # trip; only truly driver-local modules (e.g. a pytest file on a
    # pytest-inserted path) go by value.
    import os
    parts = mod_name.split(".")
    root = os.path.join(os.getcwd(), parts[0])
    if os.path.exists(root) or os.path.exists(root + ".py"):
        return
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception:  # graftlint: disable=GL004
        pass  # optional optimization; plain by-reference pickling works


def dumps(value: Any) -> bytes:
    """Plain cloudpickle dump (control-plane messages, function defs)."""
    _maybe_register_by_value(value)
    return cloudpickle.dumps(value)


def dumps_fast(value: Any) -> bytes:
    """Hot-path dump for framework-internal structures (wire messages,
    TaskSpecs): plain pickle protocol 5 (~4x cheaper than cloudpickle),
    falling back to cloudpickle when pickling fails. NOT for user
    callables/closures — those must go through dumps() so __main__
    definitions serialize by value."""
    try:
        return pickle.dumps(value, protocol=5)
    except Exception:  # noqa: BLE001 — closures, local classes, ...
        return dumps(value)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
