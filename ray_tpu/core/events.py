"""Cluster lifecycle event plane.

Capability parity with the reference's GCS-side event stores feeding
the state API and dashboard (reference: gcs_task_manager / the
node/actor/job event tables behind ``ray list cluster-events``): every
lifecycle transition — node register / heartbeat-miss / declared-dead,
worker spawn/exit, actor create/restart/dead, lease grant/retry/spill,
lineage-reconstruction start/done, serve replica start/stop, train
elastic resize — appends one bounded record to a GCS-side deque
(``Gcs.cluster_events``, same shape as the task-event buffer).

Death events mint a sequence id that the reschedule / reconstruction
events they trigger carry in ``caused_by``, so the recovery timeline of
an incident is a queryable causal chain rooted at the death event
(``devtools/recovery.py`` folds it into per-incident MTTR reports).

Emission is always-on and cheap: one tuple build plus a deque append
under the GCS lock. The hot-path record is a plain tuple::

    (seq, ts, severity, kind, node_id, worker_id, actor_id, task_id,
     message, caused_by, data)

with ids stored as hex strings (JSON-ready; ``list_cluster_events``
materializes :class:`ClusterEvent` views lazily). Config knobs:
``cluster_events_enabled`` / ``cluster_events_buffer_size``.

MTTR metrics (GL006-clean; ``*_local`` variants are used on IO-loop
paths): ``ray_tpu_core_recovery_seconds{phase}``,
``ray_tpu_core_node_deaths_total``,
``ray_tpu_core_reconstructions_total``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")

#: kind -> one-line description (the README kinds table is generated
#: from the same vocabulary; recovery.py keys its fold off these).
KINDS: Dict[str, str] = {
    "NODE_ADDED": "node registered with the control plane",
    "NODE_HEARTBEAT_MISS": "remote node overdue on heartbeats "
                           "(not yet declared dead)",
    "NODE_DEAD": "node declared dead (heartbeat timeout, connection "
                 "loss, or removal)",
    "WORKER_STARTED": "worker process spawned into a node's pool",
    "WORKER_EXIT": "worker process exited unexpectedly",
    "ACTOR_CREATED": "actor registered (creation task pending)",
    "ACTOR_ALIVE": "actor constructor finished; actor serving",
    "ACTOR_RESTARTING": "actor lost its worker; restart in flight",
    "ACTOR_DEAD": "actor permanently dead",
    "ACTOR_ORPHANED": "actor record restored without a live worker "
                      "(head restart)",
    "LEASE_GRANTED": "task leased onto a node for execution",
    "TASK_RETRY": "task resubmitted after a worker/node death",
    "OBJECT_SPILLED": "objects spilled to disk under arena pressure",
    "RECONSTRUCT_START": "lineage reconstruction of a lost object began",
    "RECONSTRUCT_DONE": "lineage reconstruction finished",
    "REPLICA_STARTED": "serve replica passed its construction health "
                       "check",
    "REPLICA_STOPPED": "serve replica stopped (downscale or health "
                       "failure)",
    "TRAIN_RESIZED": "elastic trainer chose a new world size after a "
                     "failure",
    "CHAOS_INJECTED": "deterministic fault injected by the chaos "
                      "controller (devtools/chaos.py)",
    "PG_RESCHEDULED": "placement group lost a member node; bundles "
                      "released and the gang re-queued for placement",
}

#: kinds that root a recovery incident (everything chained from one of
#: these via caused_by belongs to its timeline)
DEATH_KINDS = ("NODE_DEAD", "WORKER_EXIT", "ACTOR_DEAD")


@dataclass
class ClusterEvent:
    """Materialized view of one stored event tuple."""

    seq: int
    timestamp: float
    severity: str
    kind: str
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    actor_id: Optional[str] = None
    task_id: Optional[str] = None
    message: str = ""
    caused_by: Optional[int] = None
    data: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq, "timestamp": self.timestamp,
            "severity": self.severity, "kind": self.kind,
            "node_id": self.node_id, "worker_id": self.worker_id,
            "actor_id": self.actor_id, "task_id": self.task_id,
            "message": self.message, "caused_by": self.caused_by,
            "data": self.data,
        }

    @classmethod
    def from_tuple(cls, row: tuple) -> "ClusterEvent":
        (seq, ts, severity, kind, node_id, worker_id, actor_id,
         task_id, message, caused_by, data) = row
        return cls(seq=seq, timestamp=ts, severity=severity, kind=kind,
                   node_id=node_id, worker_id=worker_id,
                   actor_id=actor_id, task_id=task_id, message=message,
                   caused_by=caused_by, data=dict(data or {}))


def ent_hex(entity) -> Optional[str]:
    """Normalize an entity id (NodeID/WorkerID/... or str) to hex."""
    if entity is None or isinstance(entity, str):
        return entity
    to_hex = getattr(entity, "hex", None)
    if to_hex is not None:
        return to_hex() if callable(to_hex) else to_hex
    return str(entity)


def emit(kind: str, severity: str = "INFO", *, node_id=None,
         worker_id=None, actor_id=None, task_id=None, message: str = "",
         caused_by: Optional[int] = None,
         data: Optional[dict] = None) -> Optional[int]:
    """Emit one lifecycle event from anywhere: a driver appends
    directly to the GCS store; a worker routes over the control channel
    (``gcs_call("add_cluster_event")``). No-op (returns None) without a
    runtime or with ``cluster_events_enabled`` off. Driver-side core
    code on the IO loop should call ``rt.gcs.add_cluster_event``
    directly instead — same cost, no runtime lookup."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime_or_none()
    if rt is None:
        return None
    if getattr(rt, "is_driver", False):
        return rt.gcs.add_cluster_event(
            kind, severity, node_id=node_id, worker_id=worker_id,
            actor_id=actor_id, task_id=task_id, message=message,
            caused_by=caused_by, data=data)
    try:
        return rt.gcs_call(
            "add_cluster_event", kind, severity, ent_hex(node_id),
            ent_hex(worker_id), ent_hex(actor_id), ent_hex(task_id),
            message, caused_by, data)
    except Exception:  # noqa: BLE001 — observability never propagates
        return None


# --- MTTR metrics (built once, on first access) -----------------------
# gcs.py imports this module, so eager construction would recurse into
# ray_tpu.util (whose package __init__ imports gcs back). PEP 562
# module __getattr__ defers the Histogram/Counter builds to the first
# emit site touching them — after the package graph settles.
_metrics_lock = __import__("threading").Lock()
_METRIC_NAMES = ("RECOVERY_SECONDS", "NODE_DEATHS", "RECONSTRUCTIONS")


def _init_metrics():
    from ray_tpu.util.metrics import Counter, Histogram
    with _metrics_lock:
        g = globals()
        if "NODE_DEATHS" in g:
            return
        g["RECOVERY_SECONDS"] = Histogram(
            "ray_tpu_core_recovery_seconds",
            "Recovery phase durations (detect: last heartbeat -> "
            "declared dead; reschedule: death -> caused lease grant; "
            "reconstruct: lineage re-execution span)",
            boundaries=[0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0],
            tag_keys=("phase",))
        g["NODE_DEATHS"] = Counter(
            "ray_tpu_core_node_deaths_total",
            "Nodes declared dead (heartbeat timeout, connection loss, "
            "or removal)")
        g["RECONSTRUCTIONS"] = Counter(
            "ray_tpu_core_reconstructions_total",
            "Lineage reconstructions completed")


def __getattr__(name: str):
    if name in _METRIC_NAMES:
        _init_metrics()
        return globals()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
