"""Wire protocol between node manager and worker processes.

Capability parity with the reference's worker<->raylet IPC
(reference: src/ray/raylet_ipc_client/client_connection.cc) — a unix
domain socket carrying length-prefixed pickled messages. The node manager
is the hub: task dispatch, task completion, nested submission, object
resolution, and control-plane (GCS) calls all flow over the worker's one
socket. Unlike the reference there is no worker-to-worker data path yet;
on one TPU host the shared-memory arena already gives every worker
zero-copy access to every large object, so the hub only moves control
messages and small inline values.
"""

from __future__ import annotations

import os
import socket
import struct
import threading

from ray_tpu.devtools import locktrace
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import serialization

_LEN = struct.Struct("<I")

# Wire-schema versioning (reference: protocol evolution in the gRPC
# schema, src/ray/protobuf/ — proto3 tolerates unknown fields; breaking
# changes get new RPCs). Evolution policy:
#
# - PROTOCOL_VERSION (major): bump ONLY on an incompatible change to an
#   existing message's shape or meaning. Mismatched peers are rejected
#   at the handshake, never mid-stream.
# - PROTOCOL_MINOR: bump when ADDING message kinds or optional fields.
#   Peers with equal major but different minor interoperate: readers
#   use dict.get with defaults for post-v1 fields, and an unknown kind
#   from a newer peer is answered with UNSUPPORTED (not a crash), so a
#   newer node can probe and fall back.
# - The REGISTERED reply carries the head's (major, minor) and its
#   `capabilities` set; peers gate optional features on membership
#   instead of sniffing versions.
PROTOCOL_VERSION = 1
PROTOCOL_MINOR = 1

# Feature names the head advertises in REGISTERED (grow-only).
CAPABILITIES = (
    "auth-token",          # plaintext AUTH preamble frames
    "rpc-chaos",           # RTPU_RPC_CHAOS fault injection
    "pull-manager",        # prioritized pulls + byte budget
    "streaming-generators",
    "cpp-workers",         # TLV worker channel (kinds 6/7/8)
)


# --- fault injection ---------------------------------------------------
# Env-gated RPC chaos (reference: src/ray/rpc/rpc_chaos.h:24-46,
# RAY_testing_rpc_failure / RAY_testing_asio_delay_us). Spec:
#   RTPU_RPC_CHAOS="PULL=fail:2;HEARTBEAT=delay:50;*=fail:1"
# ``KIND=fail:N`` makes the first N sends of that message kind raise
# ConnectionResetError (simulating a dropped link mid-call); ``delay:MS``
# sleeps before every matching send. ``*`` matches any kind. Counts are
# per-process. Production cost when unset: one dict lookup per send.


class _RpcChaos:
    def __init__(self, spec: str):
        self.delay_ms: Dict[str, float] = {}
        self.fail_left: Dict[str, int] = {}
        self._lock = locktrace.traced_lock("core.protocol")
        for part in spec.split(";"):
            part = part.strip()
            if not part or "=" not in part:
                continue
            kind, _, action = part.partition("=")
            what, _, arg = action.partition(":")
            if what == "fail":
                self.fail_left[kind] = int(arg or 1)
            elif what == "delay":
                self.delay_ms[kind] = float(arg or 0)

    def on_send(self, kind: Optional[str]) -> None:
        if kind is None:
            kind = "?"
        for k in (kind, "*"):
            ms = self.delay_ms.get(k)
            if ms:
                time.sleep(ms / 1000.0)
        with self._lock:
            for k in (kind, "*"):
                left = self.fail_left.get(k, 0)
                if left > 0:
                    self.fail_left[k] = left - 1
                    raise ConnectionResetError(
                        f"rpc chaos: injected failure for {kind!r}")


_chaos: Optional[_RpcChaos] = None
_chaos_spec: Optional[str] = None
_chaos_build_lock = threading.Lock()


def _maybe_chaos(kind: Optional[str]) -> None:
    global _chaos, _chaos_spec
    spec = os.environ.get("RTPU_RPC_CHAOS")
    if not spec:
        if _chaos is not None:
            with _chaos_build_lock:
                _chaos = _chaos_spec = None
        return
    chaos = _chaos
    if spec != _chaos_spec or chaos is None:
        # Build under a lock so concurrent first senders don't replace
        # a live instance and reset its fail counters.
        with _chaos_build_lock:
            if spec != _chaos_spec or _chaos is None:
                _chaos_spec, _chaos = spec, _RpcChaos(spec)
            chaos = _chaos
    chaos.on_send(kind)


def retry_call(fn: Callable[[], Any], *, attempts: int = 3,
               backoff_s: float = 0.05, max_backoff_s: float = 2.0,
               retry_on: tuple = (OSError,),
               description: str = "rpc") -> Any:
    """Run ``fn`` with exponential backoff on transient transport errors.

    For IDEMPOTENT calls only (reference:
    src/ray/rpc/retryable_grpc_client.h — retries are the caller's
    promise that the server can see the request twice). Re-raises the
    last error once attempts are exhausted. Delays are jittered
    (util/backoff.py) so concurrent callers hitting the same dead link
    decorrelate instead of retrying in lockstep.
    """
    import logging

    from ray_tpu.util.backoff import Backoff
    backoff = Backoff(initial_s=backoff_s, max_s=max_backoff_s)
    for i in range(attempts):
        try:
            return fn()
        except retry_on as err:
            if i == attempts - 1:
                raise
            delay = backoff.next_delay()
            logging.getLogger("ray_tpu.rpc").debug(
                "%s failed (%s), retry %d/%d in %.2fs",
                description, err, i + 1, attempts - 1, delay)
            time.sleep(delay)


def _send_all(sock: socket.socket, data: bytes) -> None:
    """sendall that also works on non-blocking sockets (the node's
    selector loop keeps worker connections non-blocking for reads;
    writes from other threads spin on writability when the buffer
    fills)."""
    import select as _select
    view = memoryview(data)
    while view:
        try:
            sent = sock.send(view)
        except (BlockingIOError, InterruptedError):
            _select.select([], [sock], [], 1.0)
            continue
        view = view[sent:]


def send_msg(sock: socket.socket, msg: dict) -> None:
    # Messages carry only framework structures and pre-serialized bytes
    # (user values are packed upstream), so the fast pickle path is safe.
    _maybe_chaos(msg.get("kind"))
    data = serialization.dumps_fast(msg)
    _send_all(sock, _LEN.pack(len(data)) + data)


class FrameReader:
    """Incremental parser for length-prefixed frames on a non-blocking
    socket (reference: client_connection.cc async read path)."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        out: List[bytes] = []
        buf = self._buf
        while True:
            if len(buf) < _LEN.size:
                break
            (length,) = _LEN.unpack_from(buf)
            end = _LEN.size + length
            if len(buf) < end:
                break
            out.append(bytes(buf[_LEN.size:end]))
            del buf[:end]
        return out

    def leftover(self) -> bytes:
        """Unparsed buffered bytes (a partial frame tail) — consumed
        when a connection is handed off to a different protocol."""
        return bytes(self._buf)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One raw length-prefixed frame (no deserialization) — used where
    the peer's codec isn't known yet (e.g. C-API vs pickle clients on
    the head listener)."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    return _recv_exact(sock, length)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    _send_all(sock, _LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    data = recv_frame(sock)
    if data is None:
        return None
    return serialization.loads(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    remaining = n
    while remaining:
        try:
            chunk = sock.recv(remaining)
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def listen_tcp(host: str, port: int) -> socket.socket:
    """Bind + listen a TCP socket for cross-host control traffic
    (reference: grpc_server.h:81 — here length-framed messages over a
    plain stream; host defaults to loopback, pods pass the DCN address)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def connect_tcp(host: str, port: int,
                timeout: Optional[float] = None) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


def parse_address(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


class MessageConnection:
    """Thread-safe framed-message connection."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = locktrace.traced_lock("core.protocol.send")

    def send(self, msg: dict) -> None:
        _maybe_chaos(msg.get("kind"))
        data = serialization.dumps_fast(msg)
        framed = _LEN.pack(len(data)) + data
        with self._send_lock:
            _send_all(self.sock, framed)

    def recv(self) -> Optional[dict]:
        return recv_msg(self.sock)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class _PrebufferedSocket:
    """Socket wrapper that serves already-read bytes before touching
    the wire — used when a connection leaves the IO loop for a
    blocking protocol handler (C-API handoff) with bytes still sitting
    in the loop-side decode buffer."""

    def __init__(self, sock: socket.socket, pending: bytes):
        self._sock = sock
        self._pending = pending

    def recv(self, n: int) -> bytes:
        if self._pending:
            out, self._pending = self._pending[:n], self._pending[n:]
            return out
        return self._sock.recv(n)

    def __getattr__(self, name):
        return getattr(self._sock, name)


# --- message kinds (node manager <-> worker) ---------------------------
# worker -> node: REGISTER, TASK_DONE, SUBMIT, GET_OBJECT, PUT_META,
#                 GCS_REQUEST, WAIT, ACTOR_STATE
# node -> worker: EXECUTE, EXECUTE_ACTOR_TASK, CREATE_ACTOR, OBJECT_VALUE,
#                 GCS_REPLY, KILL, SHUTDOWN
