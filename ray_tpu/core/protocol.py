"""Wire protocol between node manager and worker processes.

Capability parity with the reference's worker<->raylet IPC
(reference: src/ray/raylet_ipc_client/client_connection.cc) — a unix
domain socket carrying length-prefixed pickled messages. The node manager
is the hub: task dispatch, task completion, nested submission, object
resolution, and control-plane (GCS) calls all flow over the worker's one
socket. Unlike the reference there is no worker-to-worker data path yet;
on one TPU host the shared-memory arena already gives every worker
zero-copy access to every large object, so the hub only moves control
messages and small inline values.
"""

from __future__ import annotations

import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization

_LEN = struct.Struct("<I")

# Wire-schema version (reference: protocol versioning in the gRPC
# schema, src/ray/protobuf/). Carried in the REGISTER / NODE_REGISTER
# handshakes; a mismatched peer is rejected cleanly instead of failing
# on an unknown/renamed message mid-stream. Bump on any incompatible
# message-shape change.
PROTOCOL_VERSION = 1


def _send_all(sock: socket.socket, data: bytes) -> None:
    """sendall that also works on non-blocking sockets (the node's
    selector loop keeps worker connections non-blocking for reads;
    writes from other threads spin on writability when the buffer
    fills)."""
    import select as _select
    view = memoryview(data)
    while view:
        try:
            sent = sock.send(view)
        except (BlockingIOError, InterruptedError):
            _select.select([], [sock], [], 1.0)
            continue
        view = view[sent:]


def send_msg(sock: socket.socket, msg: dict) -> None:
    # Messages carry only framework structures and pre-serialized bytes
    # (user values are packed upstream), so the fast pickle path is safe.
    data = serialization.dumps_fast(msg)
    _send_all(sock, _LEN.pack(len(data)) + data)


class FrameReader:
    """Incremental parser for length-prefixed frames on a non-blocking
    socket (reference: client_connection.cc async read path)."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        out: List[bytes] = []
        buf = self._buf
        while True:
            if len(buf) < _LEN.size:
                break
            (length,) = _LEN.unpack_from(buf)
            end = _LEN.size + length
            if len(buf) < end:
                break
            out.append(bytes(buf[_LEN.size:end]))
            del buf[:end]
        return out


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """One raw length-prefixed frame (no deserialization) — used where
    the peer's codec isn't known yet (e.g. C-API vs pickle clients on
    the head listener)."""
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    return _recv_exact(sock, length)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    _send_all(sock, _LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    data = recv_frame(sock)
    if data is None:
        return None
    return serialization.loads(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    remaining = n
    while remaining:
        try:
            chunk = sock.recv(remaining)
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def listen_tcp(host: str, port: int) -> socket.socket:
    """Bind + listen a TCP socket for cross-host control traffic
    (reference: grpc_server.h:81 — here length-framed messages over a
    plain stream; host defaults to loopback, pods pass the DCN address)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def connect_tcp(host: str, port: int,
                timeout: Optional[float] = None) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


def parse_address(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


class MessageConnection:
    """Thread-safe framed-message connection."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()

    def send(self, msg: dict) -> None:
        data = serialization.dumps_fast(msg)
        framed = _LEN.pack(len(data)) + data
        with self._send_lock:
            _send_all(self.sock, framed)

    def recv(self) -> Optional[dict]:
        return recv_msg(self.sock)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


# --- message kinds (node manager <-> worker) ---------------------------
# worker -> node: REGISTER, TASK_DONE, SUBMIT, GET_OBJECT, PUT_META,
#                 GCS_REQUEST, WAIT, ACTOR_STATE
# node -> worker: EXECUTE, EXECUTE_ACTOR_TASK, CREATE_ACTOR, OBJECT_VALUE,
#                 GCS_REPLY, KILL, SHUTDOWN
