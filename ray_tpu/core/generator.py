"""ObjectRefGenerator — incremental results from streaming tasks.

Capability parity with the reference's streaming generators
(reference: python/ray/_raylet.pyx:299 ObjectRefGenerator;
src/ray/core_worker/task_execution/generator_waiter.cc). A task or
actor method declared with ``num_returns="streaming"`` returns one of
these instead of an ObjectRef: each ``next()`` blocks until the worker
has yielded (and stored) the next value, so the consumer overlaps with
the producer — the basis for token streaming in Serve/LLM and per-block
Data returns.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef


class ObjectRefGenerator:
    """Iterates ObjectRefs of a streaming task's yields, in yield order.

    Picklable: passing a generator to another task hands over
    consumption (indices are tracked per-instance, so exactly one
    consumer should iterate a given instance).
    """

    def __init__(self, task_id: TaskID, start_index: int = 0):
        self._task_id = task_id
        self._index = start_index
        self._exhausted = False

    @property
    def task_id(self) -> TaskID:
        return self._task_id

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self._next_internal(timeout=None)

    def next_ready(self, timeout: Optional[float] = None) -> ObjectRef:
        """Like next() but with a timeout (raises GetTimeoutError)."""
        return self._next_internal(timeout=timeout)

    def _next_internal(self, timeout: Optional[float]) -> ObjectRef:
        if self._exhausted:
            raise StopIteration
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        status, payload = rt.stream_next(self._task_id, self._index, timeout)
        if status == "item":
            self._index += 1
            return ObjectRef(payload if isinstance(payload, ObjectID)
                             else ObjectID(payload))
        self._exhausted = True
        if status == "done":
            raise StopIteration
        raise payload  # the task's error

    def completed(self) -> bool:
        return self._exhausted

    def __reduce__(self):
        # Serialization hands consumption to the receiver: the local
        # copy must no longer reclaim the stream on GC (ownership
        # transfer, reference: generator refs passed between workers).
        self._handed_off = True
        return (ObjectRefGenerator, (self._task_id, self._index))

    def __del__(self):
        # Reclaim owner-side state: unconsumed items (no ObjectRef was
        # ever constructed for them) and the StreamState record itself.
        if getattr(self, "_handed_off", False):
            return
        try:
            from ray_tpu.core import runtime as runtime_mod
        except ImportError:
            return
        rt = runtime_mod.get_runtime_or_none()
        if rt is not None and getattr(rt, "is_driver", False):
            try:
                rt.release_stream(self._task_id, self._index)
            except Exception:  # graftlint: disable=GL004
                pass  # __del__ from GC; runtime may be half torn down
