"""Worker process: executes tasks and hosts actors.

Capability parity with the reference's worker side
(reference: python/ray/_private/workers/default_worker.py main loop →
CoreWorkerProcess::RunTaskExecutionLoop, core_worker_process.cc:119;
task execution via TaskReceiver, task_execution/task_receiver.h:44, with
concurrency groups running on a thread pool,
task_execution/concurrency_group_manager.h).

One process per worker; connects to its node manager over a unix socket;
executes plain tasks FIFO on a single thread (ordering guarantee) and
actor tasks on a pool of ``max_concurrency`` threads. Inside task code
the global runtime is a WorkerRuntime, so ``remote``/``get``/``put``
compose (nested tasks, actor handles in args).
"""

from __future__ import annotations

import argparse
import os
import queue
import socket
import sys
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.protocol import MessageConnection
from ray_tpu.core.task_manager import ReferenceCounter
from ray_tpu.core.task_spec import Arg, TaskSpec
from ray_tpu.devtools import refsan
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError, TaskError
from ray_tpu.util import flight_recorder as _flight


class _ContextValue:
    """threading.local-compatible ``.value`` holder backed by a
    ContextVar — isolated per thread AND per asyncio task."""

    def __init__(self, name: str):
        import contextvars
        object.__setattr__(self, "_var",
                           contextvars.ContextVar(name, default=None))

    @property
    def value(self):
        return self._var.get()

    @value.setter
    def value(self, v):
        self._var.set(v)


class WorkerRuntime:
    """The runtime visible to user code executing inside this worker."""

    def __init__(self, conn: MessageConnection, store: SharedMemoryStore,
                 node_id: NodeID, worker_id: WorkerID):
        self.conn = conn
        self.store = store
        self.node_id = node_id
        self.worker_id = worker_id
        # Borrowed-ref reporting: the first local ref to an object pins
        # it at the owner (REF_ADD); the last drop releases it
        # (REF_DROP). reference: reference_counter.h:43 borrowing.
        self.reference_counter = ReferenceCounter()
        self.reference_counter.refsan_role = "borrower"
        self.reference_counter.set_on_first(
            lambda oid: self._send_borrow("REF_ADD", oid))
        self.reference_counter.set_deleter(
            lambda oid: self._send_borrow("REF_DROP", oid))
        self.is_driver = False
        # set by worker_main: flushes queued specs back to the node
        # before this worker blocks on an object
        self.on_block = None
        self._pubsub_callbacks: Dict[str, list] = {}
        self._req_lock = threading.Lock()
        self._req_counter = 0
        self._replies: Dict[int, Tuple[threading.Event, list]] = {}
        self._fn_cache: Dict[str, Any] = {}
        self._put_counter = 0
        # contextvars, not threading.local: async-actor coroutines
        # interleave on ONE event-loop thread, and each asyncio Task
        # runs in its own context copy — a thread-local would be
        # clobbered across awaits (wrong task ids / merged spans)
        self._current_task_id = _ContextValue("current_task_id")
        # per-task user profile spans (ray_tpu.util.tracing.profile),
        # shipped with the TASK_DONE reply into the GCS event store
        self._profile_spans = _ContextValue("profile_spans")
        self.actor_instance = None
        self.actor_id: Optional[ActorID] = None
        # normalized runtime env this worker runs inside (child tasks
        # submitted from here inherit it; see runtime_env/__init__.py)
        self.current_runtime_env: Optional[dict] = None
        # set when runtime_env setup failed: every task handed to this
        # worker fails fast with this error instead of executing
        self.setup_error: Optional[Exception] = None

    def _send_borrow(self, op: str, oid) -> None:
        """Report a borrow transition to the owner; mirrored into the
        refsan ledger so the driver-side fold can pair each wire send
        with the owner's add/drop."""
        led = refsan.LEDGER
        if led is not None:
            led.record(refsan.KIND_BORROW_SEND, oid.hex(), {"op": op})
        self.conn.send({"kind": op, "object_id": oid.binary()})

    # --- request/reply with the node manager ---------------------------
    def _next_req(self) -> Tuple[int, threading.Event, list]:
        with self._req_lock:
            self._req_counter += 1
            rid = self._req_counter
            ev = threading.Event()
            slot: list = [None]
            self._replies[rid] = (ev, slot)
        return rid, ev, slot

    def request(self, msg: dict, timeout: Optional[float] = None) -> Any:
        rid, ev, slot = self._next_req()
        msg["req_id"] = rid
        self.conn.send(msg)
        if not ev.wait(timeout):
            with self._req_lock:
                self._replies.pop(rid, None)
            raise GetTimeoutError(f"request {msg.get('kind')} timed out")
        with self._req_lock:
            self._replies.pop(rid, None)
        return slot[0]

    def deliver_reply(self, msg: dict) -> None:
        rid = msg.get("req_id")
        with self._req_lock:
            entry = self._replies.get(rid)
        if entry is not None:
            ev, slot = entry
            slot[0] = msg
            ev.set()

    # --- object plane ---------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        with serialization.collect_contained_refs() as contained:
            data, buffers = serialization.serialize(value)
        return self.put_serialized(
            data, buffers, contained=[o.binary() for o in contained])

    def request_spill(self, nbytes: int) -> None:
        """Ask the owner to spill objects from this node's arena to disk
        (reference: raylet-triggered spilling under create pressure,
        local_object_manager.h:43)."""
        self.request({"kind": "SPILL_REQUEST", "bytes": nbytes},
                     timeout=60.0)

    def _store_with_spill(self, write_fn, nbytes: int):
        """Run a store write; on a full arena, spill and retry. Several
        rounds: a spilled victim's space frees only after in-flight
        readers (e.g. an object-server stream) release their pins."""
        import time as _time

        from ray_tpu.exceptions import ObjectStoreFullError
        attempts = 5
        for attempt in range(attempts):
            try:
                return write_fn()
            except ObjectStoreFullError:
                if attempt == attempts - 1:
                    raise
                self.request_spill(nbytes)
                _time.sleep(0.05 * (attempt + 1))

    def put_serialized(self, data: bytes, buffers, contained=()) -> ObjectRef:
        # Random IDs: a retried task attempt must not collide with the
        # puts of its previous attempt (the ID travels in the returned
        # ref + PUT_META, so determinism buys nothing).
        oid = ObjectID.from_random()
        sizes = [b.nbytes for b in buffers]
        nbytes = serialization.packed_size(data, sizes)
        rec = _flight.RECORDER
        t0_ns = rec.clock() if rec is not None else 0
        self._store_with_spill(
            lambda: self.store.put_parts(oid, data, buffers, sizes),
            nbytes)
        if rec is not None:
            rec.record("object", "put", t0_ns, rec.clock() - t0_ns,
                       {"oid": oid.hex()[:12], "bytes": nbytes})
        self.conn.send({"kind": "PUT_META", "object_id": oid.binary(),
                        "contained": list(contained)})
        return ObjectRef(oid)

    def put_result(self, oid: ObjectID, value: Any) -> Tuple[str, Any, list]:
        """Store a task return; small values go inline in the reply.
        Returns (kind, payload, contained_ref_binaries)."""
        with serialization.collect_contained_refs() as contained:
            data, buffers = serialization.serialize(value)
        contained_bin = [o.binary() for o in contained]
        from ray_tpu.core.config import get_config
        if not buffers and len(data) < get_config().max_inline_object_size:
            return ("inline", serialization.pack_parts(data, buffers),
                    contained_bin)
        sizes = [b.nbytes for b in buffers]
        packed_len = serialization.packed_size(data, sizes)

        def write():
            dest = self.store.create(oid, packed_len)
            try:
                serialization.pack_into(dest, data, buffers, sizes)
            finally:
                del dest
            self.store.seal(oid)

        self._store_with_spill(write, packed_len)
        return ("shm", None, contained_bin)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = []
        for ref in refs:
            out.append(self._get_one(ref.id, timeout))
        return out[0] if single else out

    def _get_one(self, oid: ObjectID, timeout: Optional[float]):
        found, value = self.store.get_value(oid, timeout_s=0.0)
        if found:
            return value
        # About to block: hand queued (pipelined) specs back to the node
        # so they can run elsewhere — one of them might be what this
        # get() is waiting for (head-of-line deadlock otherwise). Specs
        # arriving while blocked bounce straight back (enter/exit).
        if self.on_block is not None:
            self.on_block(True)
        rec = _flight.RECORDER
        t0_ns = rec.clock() if rec is not None else 0
        try:
            reply = self.request(
                {"kind": "GET_OBJECT", "object_id": oid.binary()},
                timeout=timeout if timeout is not None else None,
            )
        finally:
            if rec is not None:
                rec.record("object", "get_wait", t0_ns,
                           rec.clock() - t0_ns,
                           {"oid": oid.hex()[:12]})
            if self.on_block is not None:
                self.on_block(False)
        status = reply["status"]
        if status == "inline":
            return serialization.unpack(reply["data"])
        if status == "shm_local":
            found, value = self.store.get_value(oid, timeout_s=5.0)
            if found:
                return value
            raise ObjectLostError(oid)
        if status == "spilled_local":
            # payload was spilled to a file on this host (reference:
            # reading back from external storage)
            try:
                with open(reply["path"], "rb") as f:
                    return serialization.unpack(f.read())
            except OSError:
                raise ObjectLostError(oid)
        if status == "error":
            raise serialization.loads(reply["error"])
        raise ObjectLostError(oid)

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if num_returns > len(refs):
            raise ValueError(
                f"num_returns ({num_returns}) exceeds the number of refs "
                f"({len(refs)})")
        if self.on_block is not None:
            self.on_block(True)
            try:
                return self._wait_inner(refs, num_returns, timeout)
            finally:
                self.on_block(False)
        return self._wait_inner(refs, num_returns, timeout)

    def _wait_inner(self, refs: List[ObjectRef], num_returns: int,
                    timeout: Optional[float]):
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while True:
            ids = [r.id.binary() for r in pending]
            reply = self.request({"kind": "CHECK_READY", "object_ids": ids},
                                 timeout=30.0)
            ready_set = set(reply["ready"])
            newly = [r for r in pending if r.id.binary() in ready_set]
            pending = [r for r in pending if r.id.binary() not in ready_set]
            ready.extend(newly)
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and _time.monotonic() >= deadline:
                break
            _time.sleep(0.005)
        done = ready[:num_returns]
        rest = ready[num_returns:] + pending
        return done, rest

    # --- task/actor submission (nested) ---------------------------------
    def submit_spec(self, spec: TaskSpec) -> None:
        self.conn.send({"kind": "SUBMIT", "spec": serialization.dumps_fast(spec)})

    def create_actor(self, spec: TaskSpec, name: Optional[str] = None) -> None:
        self.submit_spec(spec)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.conn.send({"kind": "KILL_ACTOR", "actor_id": actor_id.binary(),
                        "no_restart": no_restart})

    def cancel_task(self, object_id: ObjectID, force: bool = False) -> None:
        self.conn.send({"kind": "CANCEL", "object_id": object_id.binary(),
                        "force": force})

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: Optional[float]):
        """Consume item ``index`` of a streaming task owned by the head
        (reference: ObjectRefGenerator protocol, _raylet.pyx:299)."""
        if self.on_block is not None:
            self.on_block(True)
        try:
            reply = self.request({"kind": "STREAM_NEXT",
                                  "task_id": task_id.binary(),
                                  "index": index},
                                 timeout=timeout)
        finally:
            if self.on_block is not None:
                self.on_block(False)
        status = reply["status"]
        if status == "item":
            return "item", ObjectID(reply["object_id"])
        if status == "done":
            return "done", None
        return "error", serialization.loads(reply["error"])

    # --- pubsub ----------------------------------------------------------
    def subscribe_channel(self, channel: str, callback) -> None:
        """Subscribe to a GCS pubsub channel from inside a worker
        (reference: subscriber.h:215 — workers couldn't subscribe in
        round 1). Callbacks run on the worker's socket-reader thread;
        keep them fast."""
        with self._req_lock:
            first = channel not in self._pubsub_callbacks
            self._pubsub_callbacks.setdefault(channel, []).append(callback)
        if first:
            self.conn.send({"kind": "SUBSCRIBE", "channel": channel})

    def publish_channel(self, channel: str, message: Any) -> None:
        self.gcs_call("publish", channel, serialization.dumps(message))

    def _on_pubsub(self, msg: dict) -> None:
        with self._req_lock:
            callbacks = list(self._pubsub_callbacks.get(msg["channel"], ()))
        payload = serialization.loads(msg["data"])
        for cb in callbacks:
            try:
                cb(payload)
            except Exception:  # noqa: BLE001 — user callback
                import traceback
                traceback.print_exc()

    # --- control plane --------------------------------------------------
    def gcs_call(self, method: str, *args, timeout: float = 30.0) -> Any:
        reply = self.request({"kind": "GCS_REQUEST", "method": method,
                              "args": serialization.dumps(args)},
                             timeout=timeout)
        if reply.get("error"):
            raise serialization.loads(reply["error"])
        return serialization.loads(reply["result"])

    def get_function(self, function_id: str):
        fn = self._fn_cache.get(function_id)
        if fn is None:
            blob = self.gcs_call("get_function", function_id)
            if blob is None:
                raise RuntimeError(f"function {function_id} not found in GCS")
            fn = serialization.loads(blob)
            # benign race: concurrent misses both fetch; last write
            # wins and both values are identical deserializations.
            # Taking _req_lock here would serialize GCS fetches.
            self._fn_cache[function_id] = fn  # graftlint: disable=GL001
        return fn

    def put_function(self, function_id: str, blob: bytes) -> None:
        self.gcs_call("put_function", function_id, blob)

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    def node_labels(self) -> Dict[str, str]:
        return self.gcs_call("node_labels", self.node_id.binary())

    def as_future(self, ref: ObjectRef):
        from concurrent.futures import Future
        fut: Future = Future()
        def run():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:
                fut.set_exception(e)
        threading.Thread(target=run, daemon=True).start()
        return fut


def _resolve_arg(rt: WorkerRuntime, arg: Arg) -> Any:
    if arg.value_bytes is not None:
        return serialization.unpack(arg.value_bytes)
    return rt._get_one(arg.object_id, timeout=None)


def _resolve_args(rt: WorkerRuntime, spec: TaskSpec):
    args = [_resolve_arg(rt, a) for a in spec.args]
    kwargs = {k: _resolve_arg(rt, a) for k, a in spec.kwargs.items()}
    return args, kwargs


def _stream_item(rt: WorkerRuntime, spec: TaskSpec, index: int,
                 value: Any) -> None:
    """Store one yielded value and report it to the owner incrementally
    (reference: streaming-generator intermediate returns,
    generator_waiter.cc)."""
    oid = ObjectID.from_random()
    kind, data, contained = rt.put_result(oid, value)
    rt.conn.send({"kind": "STREAM_ITEM", "task_id": spec.task_id.binary(),
                  "object_id": oid.binary(), "index": index,
                  "item_kind": kind, "data": data, "contained": contained})


def _stream_out(rt: WorkerRuntime, spec: TaskSpec, result: Any) -> int:
    """Drain a (a)sync generator, reporting each yield. Returns count."""
    import inspect

    if inspect.isasyncgen(result):
        import asyncio

        async def drain():
            count = 0
            async for value in result:
                _stream_item(rt, spec, count, value)
                count += 1
            return count

        return asyncio.run(drain())
    count = 0
    for value in result:
        _stream_item(rt, spec, count, value)
        count += 1
    return count


def _call_target(rt: WorkerRuntime, spec: TaskSpec, args, kwargs) -> Any:
    if spec.actor_id is not None and not spec.is_actor_creation:
        if spec.method_name == "__ray_call__":
            # run an arbitrary function against the actor instance
            # (reference: ActorHandle.__ray_call__ convention used by
            # compiled graphs to install execution loops)
            fn = args[0]
            return fn(rt.actor_instance, *args[1:], **kwargs)
        method = getattr(rt.actor_instance, spec.method_name)
        return method(*args, **kwargs)
    fn = rt.get_function(spec.function_id)
    return fn(*args, **kwargs)


def _pack_reply(rt: WorkerRuntime, spec: TaskSpec, reply: dict,
                result_values: List[Any]) -> dict:
    results = []
    for oid, value in zip(spec.return_ids(), result_values):
        kind, data, contained = rt.put_result(oid, value)
        results.append((oid.binary(), kind, data, contained))
    reply["results"] = results
    reply["error"] = None
    return reply


def _pack_stream_reply(reply: dict, count: int) -> dict:
    reply["stream_len"] = count
    reply["results"] = []
    reply["error"] = None
    return reply


def _pack_error(spec: TaskSpec, reply: dict) -> dict:
    tb = traceback.format_exc()
    # Ship the original exception as .cause when it pickles — callers
    # can catch-and-unwrap domain errors (util.queue Full/Empty, user
    # exception types) instead of string-matching the traceback
    # (reference: RayTaskError.cause, exceptions.py).
    import sys
    exc = sys.exc_info()[1]
    try:
        err = TaskError(spec.name or spec.function_id, tb, exc)
        blob = serialization.dumps(err)
    except Exception:
        err = TaskError(spec.name or spec.function_id, tb, None)
        blob = serialization.dumps(err)
    reply["results"] = []
    reply["error"] = blob
    reply["error_str"] = tb
    return reply


def _enter_trace(spec: TaskSpec):
    """Re-establish the submitter's trace context for this task's
    execution: the task itself is a span (id derived from the task id),
    so nested ``.remote()`` calls and ``tracing.span()`` blocks inside
    user code attach to the same trace. Returns the reset token."""
    from ray_tpu.util import tracing
    if spec.trace_id is None:
        return tracing.set_trace_context(None)
    return tracing.set_trace_context(tracing.TraceContext(
        spec.trace_id, tracing.task_span_id(spec.task_id)))


def _exit_trace(token) -> None:
    from ray_tpu.util import tracing
    tracing.reset_trace_context(token)


def _execute(rt: WorkerRuntime, spec: TaskSpec) -> dict:
    """Run one task/actor-task; returns the TASK_DONE message."""
    rt._current_task_id.value = spec.task_id
    trace_token = _enter_trace(spec)
    reply: dict = {"kind": "TASK_DONE", "task_id": spec.task_id.binary(),
                   "spec_is_actor_creation": spec.is_actor_creation}
    if rt.setup_error is not None:
        reply["results"] = []
        reply["error"] = serialization.dumps(rt.setup_error)
        reply["error_str"] = str(rt.setup_error)
        return reply
    import time as _time
    rt._profile_spans.value = []
    reply["t_start"] = _time.time()
    try:
        args, kwargs = _resolve_args(rt, spec)
        if spec.is_actor_creation:
            cls = rt.get_function(spec.function_id)
            rt.actor_instance = cls(*args, **kwargs)
            rt.actor_id = spec.actor_id
            result_values = [None]
        else:
            result = _call_target(rt, spec, args, kwargs)
            if spec.num_returns == -1:
                return _pack_stream_reply(
                    reply, _stream_out(rt, spec, result))
            result_values = _split_returns(result, spec.num_returns)
        return _pack_reply(rt, spec, reply, result_values)
    except Exception:  # noqa: BLE001 — user code may raise anything
        return _pack_error(spec, reply)
    finally:
        reply["t_end"] = _time.time()
        spans = rt._profile_spans.value
        if spans:
            reply["profile"] = spans
        rt._current_task_id.value = None
        _exit_trace(trace_token)


async def _execute_async(rt: WorkerRuntime, spec: TaskSpec) -> dict:
    """Async-actor execution: awaits coroutine methods and drains async
    generators on the actor's event loop, so ``max_concurrency``
    requests interleave at await points (reference: asyncio actors,
    task_execution/concurrency_group_manager.h + fiber.h)."""
    import asyncio
    import inspect

    rt._current_task_id.value = spec.task_id
    trace_token = _enter_trace(spec)
    reply: dict = {"kind": "TASK_DONE", "task_id": spec.task_id.binary(),
                   "spec_is_actor_creation": False}
    import time as _time
    rt._profile_spans.value = []
    reply["t_start"] = _time.time()
    loop = asyncio.get_running_loop()
    try:
        # Argument resolution may block on object fetches; keep the loop
        # free for other coroutines.
        args, kwargs = await loop.run_in_executor(
            None, _resolve_args, rt, spec)
        result = _call_target(rt, spec, args, kwargs)
        if inspect.iscoroutine(result):
            result = await result
        if spec.num_returns == -1:
            if inspect.isasyncgen(result):
                count = 0
                async for value in result:
                    _stream_item(rt, spec, count, value)
                    count += 1
            else:
                count = await loop.run_in_executor(
                    None, _stream_out, rt, spec, result)
            return _pack_stream_reply(reply, count)
        return _pack_reply(rt, spec, reply,
                           _split_returns(result, spec.num_returns))
    except Exception:  # noqa: BLE001 — user code may raise anything
        return _pack_error(spec, reply)
    finally:
        reply["t_end"] = _time.time()
        spans = rt._profile_spans.value
        if spans:
            reply["profile"] = spans
        rt._current_task_id.value = None
        _exit_trace(trace_token)


def _split_returns(result: Any, num_returns: int) -> List[Any]:
    if num_returns == 1:
        return [result]
    result = list(result)
    if len(result) != num_returns:
        raise ValueError(
            f"task declared num_returns={num_returns} but returned "
            f"{len(result)} values")
    return result


def worker_main(socket_path: str, node_id_hex: str, worker_id_hex: str,
                store_name: str) -> None:
    import faulthandler
    import signal
    faulthandler.register(signal.SIGUSR1)  # kill -USR1 <pid> dumps stacks
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(socket_path)
    conn = MessageConnection(sock)
    store = SharedMemoryStore(store_name)
    node_id = NodeID.from_hex(node_id_hex)
    worker_id = WorkerID.from_hex(worker_id_hex)
    rt = WorkerRuntime(conn, store, node_id, worker_id)

    from ray_tpu.core import runtime as runtime_mod
    runtime_mod.set_runtime(rt)

    # Flight recorder: enable + start the journal flusher when the
    # driver turned it on (flag rides the inherited environment).
    from ray_tpu.util import flight_recorder
    flight_recorder.init_worker(rt, worker_id)
    # Lifetime sanitizer: same inherit-the-env contract — the ledger and
    # its push flusher start only when the driver exported RAY_TPU_REFSAN.
    refsan.init_worker(rt, worker_id)
    # Collective-program sanitizer: fingerprint ledger + pusher start
    # only when the driver exported RAY_TPU_COLLSAN.
    from ray_tpu.devtools import collsan
    collsan.init_worker(rt, worker_id)
    # Sampling profiler: sampler + profile pusher start only when the
    # driver ran with RAY_TPU_PROFILER (env rides into this process).
    from ray_tpu.devtools import profiler
    profiler.init_worker(rt, worker_id)

    from ray_tpu.core.protocol import PROTOCOL_VERSION
    conn.send({"kind": "REGISTER", "worker_id": worker_id.binary(),
               "pid": os.getpid(), "proto_version": PROTOCOL_VERSION})

    # Apply this worker's runtime env (env_vars / working_dir /
    # py_modules) before any task can run; messages arriving during the
    # blocking KV fetches are deferred into the main loop (ray_tpu/
    # runtime_env/worker_setup.py). pip envs were handled pre-connect.
    deferred_msgs: List[dict] = []
    pip_error = os.environ.get("RTPU_PIP_ERROR")
    if pip_error:
        from ray_tpu.exceptions import RuntimeEnvSetupError
        rt.setup_error = RuntimeEnvSetupError(
            f"runtime_env setup failed: {pip_error}")
    renv_json = os.environ.get("RTPU_RUNTIME_ENV")
    if renv_json and rt.setup_error is None:
        import json as _json
        from ray_tpu.runtime_env import worker_setup
        try:
            worker_setup.apply_runtime_env(renv_json, conn, deferred_msgs)
            rt.current_runtime_env = _json.loads(renv_json)
        except Exception as setup_exc:  # noqa: BLE001
            # A broken env (bad URI, failed extract) must fail the tasks
            # that require it — not crash-loop the worker pool. The
            # worker stays alive and replies RuntimeEnvSetupError to
            # every spec it is handed (_execute short-circuit).
            from ray_tpu.exceptions import RuntimeEnvSetupError
            traceback.print_exc()
            rt.setup_error = RuntimeEnvSetupError(
                f"runtime_env setup failed: {setup_exc!r}")

    exec_pool = ThreadPoolExecutor(max_workers=1)
    pool_lock = threading.Lock()
    # Plain tasks run off a local pending queue on one runner thread;
    # when the current task blocks on an object, queued specs are handed
    # BACK to the node (RETURN_SPECS) so they can run elsewhere — a
    # pipelined batch-mate might be exactly what the task waits for.
    from collections import deque as _deque
    pending: "_deque" = _deque()  # (spec, collector | None)
    pending_cv = threading.Condition()

    class BatchCollector:
        """Aggregates one EXECUTE_BATCH's replies into TASK_DONE_BATCH
        (specs given back reduce the expected count)."""

        def __init__(self, expected: int):
            self.expected = expected
            self.items: list = []

        def add(self, item: dict) -> None:
            with pending_cv:
                self.items.append(item)
                done = len(self.items) >= self.expected
                items = list(self.items) if done else None
            if done:
                conn.send({"kind": "TASK_DONE_BATCH", "items": items})

        def returned(self, count: int) -> None:
            # called under pending_cv
            self.expected -= count
            if self.items and len(self.items) >= self.expected:
                items = list(self.items)
                conn.send({"kind": "TASK_DONE_BATCH", "items": items})

    blocked_depth = [0]

    def on_block(entering: bool) -> None:
        # Explicit blocked/unblocked reports keep the node's pool-cap
        # accounting exact even when a get() times out locally (the
        # node can't infer the unblock from a reply it never sent).
        with pending_cv:
            blocked_depth[0] += 1 if entering else -1
            if not entering:
                notify = blocked_depth[0] == 0
                ids = []
            else:
                notify = blocked_depth[0] == 1
                taken = list(pending)
                pending.clear()
                ids = []
                for spec, collector in taken:
                    ids.append(spec.task_id.binary())
                    if collector is not None:
                        collector.returned(1)
        if entering and ids:
            conn.send({"kind": "RETURN_SPECS", "task_ids": ids})
        if notify:
            conn.send({"kind": "BLOCKED" if entering else "UNBLOCKED"})

    rt.on_block = on_block

    def log_rotation_loop() -> None:
        """Bound this worker's log file: a chatty long-lived worker must
        not fill the disk (reference: rotated worker logs in the session
        dir). At the cap, keep one .1 backup and dup2 a fresh file over
        stdout/stderr — O_APPEND writers continue seamlessly."""
        from ray_tpu.core.config import get_config
        log_path = os.environ.get("RTPU_WORKER_LOG")
        cap = get_config().worker_log_max_bytes
        if not log_path or cap <= 0:
            return
        import time as _time
        while True:
            _time.sleep(30.0)
            try:
                if os.path.getsize(log_path) <= cap:
                    continue
                os.replace(log_path, log_path + ".1")
                fd = os.open(log_path,
                             os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
                os.dup2(fd, 1)
                os.dup2(fd, 2)
                os.close(fd)
            except OSError:
                pass

    threading.Thread(target=log_rotation_loop, name="log-rotate",
                     daemon=True).start()

    def runner_loop() -> None:
        while True:
            with pending_cv:
                while not pending:
                    pending_cv.wait()
                spec, collector = pending.popleft()
            reply = _execute(rt, spec)
            if collector is None:
                conn.send(reply)
            else:
                collector.add(reply)
            if rt.setup_error is not None:
                # A setup-failed worker must not rejoin the idle pool —
                # a transient cause (GCS blip) would otherwise poison
                # this env's sub-pool forever. Fail what we were handed,
                # then die so the node respawns a clean worker.
                with pending_cv:
                    drained = not pending
                if drained:
                    os._exit(1)

    threading.Thread(target=runner_loop, name="task-runner",
                     daemon=True).start()

    def enqueue(spec: TaskSpec, collector=None) -> None:
        with pending_cv:
            if blocked_depth[0] > 0:
                # runner is blocked on an object: bounce the spec back
                # immediately rather than parking it behind the block
                if collector is not None:
                    collector.returned(1)
                bounce = spec.task_id.binary()
            else:
                pending.append((spec, collector))
                pending_cv.notify()
                return
        conn.send({"kind": "RETURN_SPECS", "task_ids": [bounce]})
    # Async-actor support (reference: asyncio actors — the reference runs
    # coroutine methods on a dedicated event loop so max_concurrency
    # requests interleave at awaits rather than occupying threads).
    actor_state = {"loop": None, "sem": None, "max_concurrency": 1}

    def run_task(spec: TaskSpec):
        reply = _execute(rt, spec)
        conn.send(reply)
        if rt.setup_error is not None:
            os._exit(1)  # see runner_loop: don't poison the pool

    def ensure_actor_loop():
        import asyncio
        if actor_state["loop"] is None:
            loop = asyncio.new_event_loop()
            threading.Thread(target=loop.run_forever,
                             name="actor-loop", daemon=True).start()
            actor_state["loop"] = loop
            actor_state["sem"] = asyncio.Semaphore(
                actor_state["max_concurrency"])
        return actor_state["loop"]

    def run_async_task(spec: TaskSpec):
        import asyncio

        async def run():
            async with actor_state["sem"]:
                reply = await _execute_async(rt, spec)
                conn.send(reply)

        asyncio.run_coroutine_threadsafe(run(), ensure_actor_loop())

    def is_async_actor() -> bool:
        """An actor with ANY coroutine/async-gen method runs ALL its
        methods on the event loop (reference semantics: sync methods of
        asyncio actors execute on the loop, serialized with the rest) —
        per-method routing would let a sync and an async method of a
        max_concurrency=1 actor run concurrently."""
        cached = actor_state.get("is_async")
        if cached is not None:
            return cached
        import inspect
        instance = rt.actor_instance
        if instance is None:
            return False
        # getattr_static: never trigger @property getters or other
        # descriptors — a raising getter must not kill the worker.
        result = False
        for name in dir(type(instance)):
            if name.startswith("__"):
                continue
            attr = inspect.getattr_static(type(instance), name, None)
            if (inspect.iscoroutinefunction(attr)
                    or inspect.isasyncgenfunction(attr)):
                result = True
                break
        actor_state["is_async"] = result
        return result

    def handle_msg(msg: dict) -> bool:
        nonlocal exec_pool
        kind = msg["kind"]
        if kind == "EXECUTE_BATCH":
            # Batched dispatch: execute sequentially off the pending
            # queue, reply once — the head's single IO thread amortizes
            # its per-message cost across the batch.
            specs: List[TaskSpec] = serialization.loads(msg["specs"])
            collector = BatchCollector(len(specs))
            for s in specs:
                enqueue(s, collector)
        elif kind == "EXECUTE":
            enqueue(serialization.loads(msg["spec"]))
        elif kind in ("CREATE_ACTOR", "EXECUTE_ACTOR_TASK"):
            spec: TaskSpec = serialization.loads(msg["spec"])
            if spec.is_actor_creation and spec.max_concurrency > 1:
                with pool_lock:
                    exec_pool = ThreadPoolExecutor(max_workers=spec.max_concurrency)
            if spec.is_actor_creation:
                actor_state["max_concurrency"] = max(1, spec.max_concurrency)
            if kind == "EXECUTE_ACTOR_TASK" and is_async_actor():
                run_async_task(spec)
            else:
                exec_pool.submit(run_task, spec)
        elif kind in ("OBJECT_VALUE", "GCS_REPLY", "READY_REPLY",
                      "STREAM_REPLY", "SPILL_REPLY"):
            rt.deliver_reply(msg)
        elif kind == "PUBSUB_MSG":
            rt._on_pubsub(msg)
        elif kind == "SHUTDOWN":
            return False
        elif kind == "KILL":
            os._exit(1)
        return True

    for msg in deferred_msgs:
        if not handle_msg(msg):
            os._exit(0)
    while True:
        msg = conn.recv()
        if msg is None or not handle_msg(msg):
            break
    os._exit(0)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--socket", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--store-name", required=True)
    args = parser.parse_args()
    # pip/conda runtime envs must take effect before this process
    # touches its node connection: build (or reuse) the cached
    # venv/conda env and re-exec into its interpreter (exec closes the
    # not-yet-opened socket safely; RTPU_PIP_READY breaks the loop on
    # the second pass).
    renv_json = os.environ.get("RTPU_RUNTIME_ENV")
    if renv_json and not os.environ.get("RTPU_PIP_READY"):
        import json as _json
        renv = _json.loads(renv_json) or {}
        pip_spec = renv.get("pip")
        conda_spec = renv.get("conda")
        python = None
        try:
            if pip_spec:
                from ray_tpu.runtime_env.pip_env import ensure_pip_env
                python = ensure_pip_env(pip_spec)
            elif conda_spec:
                from ray_tpu.runtime_env.conda_env import ensure_conda_env
                python = ensure_conda_env(conda_spec)
        except Exception as exc:  # noqa: BLE001
            # Still connect and register: the failure must travel to
            # the requesting task as RuntimeEnvSetupError, not
            # strand the spec in the node's dispatch queue.
            os.environ["RTPU_PIP_ERROR"] = repr(exc)
        else:
            if python is not None:
                os.environ["RTPU_PIP_READY"] = "1"
                os.execve(
                    python,
                    [python, "-m", "ray_tpu.core.worker"] + sys.argv[1:],
                    dict(os.environ))
    worker_main(args.socket, args.node_id, args.worker_id, args.store_name)


if __name__ == "__main__":
    main()
