"""Unique identifiers for objects, tasks, actors, nodes, and jobs.

Capability parity with the reference's ID substrate
(reference: src/ray/common/id.h) — fixed-width binary IDs with hex
rendering, random generation, and deterministic derivation of return-object
IDs from task IDs. The layout here is simpler (no embedded flag words): a
TaskID is 16 random bytes; the i-th return object of a task is
sha1(task_id || index)[:16].
"""

from __future__ import annotations

import hashlib
import itertools
import os
import struct
import threading
from typing import ClassVar

# Fast unique-ID generation: per-process random 128-bit state mixed
# with a counter so BOTH 8-byte halves vary per ID (consumers truncate
# ids — e.g. shm segment names — so no fixed prefix may appear), and
# ids from different processes never collide beyond birthday odds
# (reference: id.h generates from a per-worker context rather than
# calling the OS RNG per ID). os.urandom per ID costs ~20us and was a
# top-5 entry in the task-submission profile; this is ~0.4us.
# Fork-safety: state is re-drawn when the PID changes.
_PACK_QQ = struct.Struct("<QQ").pack
_M64 = (1 << 64) - 1
_gen_lock = threading.Lock()
_gen_pid = 0
_gen_hi = 0
_gen_lo = 0
_gen_seq = itertools.count(1)


def _reseed(pid: int) -> None:
    """(Re)draw the per-process state. _gen_pid is published LAST so a
    concurrent caller either sees the old pid (and re-enters under the
    lock) or a fully initialized generation — never zero/stale state."""
    global _gen_pid, _gen_hi, _gen_lo, _gen_seq
    _gen_hi, _gen_lo = struct.unpack("<QQ", os.urandom(16))
    _gen_seq = itertools.count(1)
    _gen_pid = pid


_reseed(os.getpid())


def _unique16() -> bytes:
    pid = os.getpid()
    if pid != _gen_pid:  # forked child: re-draw under the lock
        with _gen_lock:
            if pid != _gen_pid:
                _reseed(pid)
    n = next(_gen_seq)
    return _PACK_QQ(_gen_hi ^ n, (_gen_lo + n) & _M64)


class BaseID:
    SIZE: ClassVar[int] = 16
    __slots__ = ("_bytes", "_hash", "_hex")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)
        self._hex = None

    @classmethod
    def from_random(cls):
        if cls.SIZE == 16:
            return cls(_unique16())
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        # cached: IDs render into events/spans/log keys many times each
        h = self._hex
        if h is None:
            h = self._hex = self._bytes.hex()
        return h

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        # cached: IDs key nearly every hot-path dict (tasks, objects,
        # locations, refcounts)
        return self._hash

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def for_actor_creation(cls, actor_id: "ActorID") -> "TaskID":
        h = hashlib.sha1(b"actor_creation:" + actor_id.binary()).digest()
        return cls(h[: cls.SIZE])


class ActorID(BaseID):
    SIZE = 12


class PlacementGroupID(BaseID):
    SIZE = 12


class ObjectID(BaseID):
    """An object id, derived from the producing task (ownership model).

    reference: src/ray/common/id.h ObjectID::FromIndex — return objects are
    addressable before the task runs, enabling futures and lineage.
    """

    SIZE = 16

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        h = hashlib.sha1(task_id.binary() + index.to_bytes(4, "little")).digest()
        return cls(h[: cls.SIZE])

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        h = hashlib.sha1(
            b"put:" + task_id.binary() + put_index.to_bytes(4, "little")
        ).digest()
        return cls(h[: cls.SIZE])
