"""Node-local object storage: shared-memory store + in-process memory store.

Capability parity with the reference's two-tier object storage
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h:48
for small/inline objects; store_provider/plasma_store_provider.h:94 +
src/ray/object_manager/plasma/ for large shared-memory objects). Small
objects live in the owner's in-process store and are inlined into task
specs; large objects are packed once into the node's shared-memory arena
(native C++ store, ray_tpu/native/src/shm_store.cc) and read zero-copy by
every worker on the node.
"""

from __future__ import annotations

import ctypes
import threading
import time
from multiprocessing import shared_memory
from typing import Any, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.devtools import refsan
from ray_tpu.exceptions import ObjectStoreFullError
from ray_tpu.native import _lib


class SharedMemoryStore:
    """A view onto the node's shared-memory object arena."""

    def __init__(self, name: str, size: int = 0, create: bool = False,
                 max_objects: int = 8192):
        self._lib = _lib.load()
        self.name = name
        if create:
            overhead = self._lib.shm_required_overhead(max_objects)
            total = size + overhead
            self._shm = shared_memory.SharedMemory(name=name, create=True, size=total)
            self._base = self._base_ptr()
            rc = self._lib.shm_init(self._base, self._shm.size, max_objects)
            if rc != _lib.OK:
                raise RuntimeError(f"shm_init failed: {rc}")
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # Attachers must not unlink the segment at exit; only the
            # creating node owns its lifetime.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # graftlint: disable=GL004
                pass  # tracker API is CPython-internal; attach still works
            self._base = self._base_ptr()
            rc = self._lib.shm_attach(self._base)
            if rc != _lib.OK:
                raise RuntimeError(f"shm_attach failed: {rc}")
        self._owner = create

    def _base_ptr(self) -> int:
        return ctypes.addressof(ctypes.c_char.from_buffer(self._shm.buf))

    def arena_range(self) -> tuple:
        """[base, base+size) of the mapped arena in THIS process. Lets
        callers prove a deserialized buffer is a zero-copy view into
        shared memory (its address lies inside the range) rather than a
        heap copy."""
        return (self._base, self._base + self._shm.size)

    # -- raw object ops -------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> memoryview:
        cfg = get_config()
        off = ctypes.c_uint64()
        for attempt in range(cfg.object_store_full_max_retries):
            rc = self._lib.shm_create(self._base, object_id.binary(), size,
                                      ctypes.byref(off))
            if rc == _lib.OK:
                led = refsan.LEDGER
                if led is not None:
                    led.slot_alloc(self.name, object_id.binary(),
                                   off.value, size)
                return self._shm.buf[off.value : off.value + size]
            if rc == _lib.EXISTS:
                raise FileExistsError(object_id)
            if rc == _lib.FULL:
                self._lib.shm_evict(self._base, size)
                time.sleep(cfg.object_store_full_retry_s)
                continue
            raise RuntimeError(f"shm_create failed: {rc}")
        raise ObjectStoreFullError(
            f"object store full: need {size} bytes, "
            f"{self.total_bytes() - self.used_bytes()} free"
        )

    def seal(self, object_id: ObjectID) -> None:
        rc = self._lib.shm_seal(self._base, object_id.binary())
        if rc != _lib.OK:
            raise RuntimeError(f"shm_seal failed: {rc}")

    def get_buffer(self, object_id: ObjectID,
                   timeout_s: float = 0.0) -> Optional[memoryview]:
        """Pin + return the payload view; None if absent within timeout."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.shm_get(self._base, object_id.binary(), timeout_s,
                               ctypes.byref(off), ctypes.byref(size))
        if rc == _lib.OK:
            led = refsan.LEDGER
            if led is not None:
                led.slot_pin(self.name, object_id.binary(),
                             off.value, size.value)
            return self._shm.buf[off.value : off.value + size.value]
        if rc in (_lib.NOT_FOUND, _lib.TIMEOUT, _lib.BAD_STATE):
            return None
        raise RuntimeError(f"shm_get failed: {rc}")

    def release(self, object_id: ObjectID) -> None:
        # May fire from GC (value-lifetime pins) after close(): no-op
        # rather than a native call on an unmapped arena.
        if self._base is None:
            return
        led = refsan.LEDGER
        if led is not None:
            led.slot_release(self.name, object_id.binary())
        self._lib.shm_release(self._base, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        if self._base is None:
            return False
        return bool(self._lib.shm_contains(self._base, object_id.binary()))

    def delete(self, object_id: ObjectID) -> None:
        if self._base is None:
            return
        led = refsan.LEDGER
        if led is not None:
            led.on_slot_delete(self.name, object_id.binary())
            if led.canary and hasattr(self._lib, "shm_delete_poison"):
                # Eviction canary: poison the payload under the store
                # lock iff the slot is really freed (a reader-pinned
                # slot is left intact — its free is deferred), then
                # sweep this process's live views against the poison.
                self._lib.shm_delete_poison(self._base, object_id.binary(),
                                            refsan.POISON_BYTE)
                led.verify_views()
                return
        self._lib.shm_delete(self._base, object_id.binary())

    def used_bytes(self) -> int:
        return self._lib.shm_used_bytes(self._base)

    def total_bytes(self) -> int:
        return self._lib.shm_total_bytes(self._base)

    def num_objects(self) -> int:
        return self._lib.shm_num_objects(self._base)

    # -- value ops ------------------------------------------------------
    def put_value(self, object_id: ObjectID, value: Any) -> int:
        """Serialize ``value`` straight into the arena. Returns byte size."""
        data, buffers = serialization.serialize(value)
        sizes = [b.nbytes for b in buffers]
        total = serialization.packed_size(data, sizes)
        dest = self.create(object_id, total)
        try:
            serialization.pack_into(dest, data, buffers, sizes)
        finally:
            del dest  # release buffer view before seal (shm.buf exports)
        self.seal(object_id)
        return total

    def put_parts(self, object_id: ObjectID, data: bytes,
                  buffers, sizes) -> int:
        """Write pre-serialized parts (one serialize pass upstream)."""
        total = serialization.packed_size(data, sizes)
        dest = self.create(object_id, total)
        try:
            serialization.pack_into(dest, data, buffers, sizes)
        finally:
            del dest
        self.seal(object_id)
        return total

    def put_packed(self, object_id: ObjectID, packed: bytes) -> int:
        dest = self.create(object_id, len(packed))
        try:
            dest[:] = packed
        finally:
            del dest
        self.seal(object_id)
        return len(packed)

    def get_value(self, object_id: ObjectID, timeout_s: float = 0.0):
        """Returns (found, value). Zero-copy for large numpy payloads: the
        reader pin taken by get_buffer is released only when the
        deserialized value itself is garbage-collected, so views into the
        arena stay valid even after the ObjectRef is dropped (the store
        defers freeing deleted-but-pinned objects; reference: plasma
        buffers pinning the object for the value's lifetime)."""
        buf = self.get_buffer(object_id, timeout_s)
        if buf is None:
            return False, None
        released = []

        def on_release():
            if not released:
                released.append(True)
                try:
                    self.release(object_id)
                except Exception:  # graftlint: disable=GL004
                    pass  # runs from GC/interpreter shutdown

        try:
            if refsan.LEDGER is not None:
                # Name the object for view registration so the canary
                # checker can attribute dangling views to their oid.
                with refsan.view_context(object_id.hex()):
                    value = serialization.unpack_pinned(buf, on_release)
            else:
                value = serialization.unpack_pinned(buf, on_release)
        except BaseException:
            del buf
            on_release()
            raise
        return True, value

    def close(self):
        # Drop the ctypes export before closing the mapping.
        self._base = None
        try:
            self._shm.close()
        except BufferError:
            pass  # outstanding zero-copy views; mapping stays until GC
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


def spill_objects(store: SharedMemoryStore, spill_dir: str, object_ids,
                  needed: int):
    """Spill sealed objects from `store` to files until `needed` bytes
    are freed (reference: LocalObjectManager::SpillObjects,
    local_object_manager.h:43). Returns [(ObjectID, path, size)].
    Shared by the head (in-process nodes) and node daemons."""
    import os

    os.makedirs(spill_dir, exist_ok=True)
    results = []
    freed = 0
    for oid in object_ids:
        if freed >= needed:
            break
        buf = store.get_buffer(oid, timeout_s=0)
        if buf is None:
            continue
        path = os.path.join(spill_dir, oid.hex())
        try:
            size = len(buf)
            with open(path, "wb") as f:
                f.write(buf)
        finally:
            del buf
            store.release(oid)
        store.delete(oid)
        results.append((oid, path, size))
        freed += size
    return results


class MemoryStore:
    """In-process store for small objects and pending futures."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, Any] = {}
        self._events: Dict[ObjectID, threading.Event] = {}

    def put(self, object_id: ObjectID, value: Any) -> None:
        with self._lock:
            self._objects[object_id] = value
            ev = self._events.pop(object_id, None)
        if ev is not None:
            ev.set()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get(self, object_id: ObjectID, timeout_s: Optional[float] = None):
        """Returns (found, value); blocks up to timeout_s for pending puts."""
        with self._lock:
            if object_id in self._objects:
                return True, self._objects[object_id]
            if timeout_s == 0:
                return False, None
            ev = self._events.setdefault(object_id, threading.Event())
        if not ev.wait(timeout_s):
            return False, None
        with self._lock:
            if object_id in self._objects:
                return True, self._objects[object_id]
        return False, None

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)
            self._events.pop(object_id, None)
