"""GCS — the cluster control plane.

Capability parity with the reference's Global Control Service
(reference: src/ray/gcs/gcs_server.h:98): node table
(gcs_node_manager.h:47), actor table + restart policy
(gcs_actor_manager.h:93), job table (gcs_job_manager.h:50), cluster-wide
KV (gcs_kv_manager.cc), function store (gcs_function_manager.h), pubsub
(pubsub_handler.cc), task-event store (gcs_task_manager.h:97), placement
groups (gcs_placement_group_manager.h:50), and health checking
(gcs_health_check_manager.h:45).

The GCS lives in the head (driver) process; workers reach it through
their node manager socket (GCS_REQUEST messages). All tables share one
lock — the control plane is low-rate (scheduling, registration, state
changes), while the data plane rides shared memory / ICI and never
touches the GCS, matching the reference's separation.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import events as events_mod
from ray_tpu.core.config import get_config
from ray_tpu.core.events import ClusterEvent, ent_hex
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID, WorkerID
from ray_tpu.core.task_spec import TaskEvent, TaskSpec
from ray_tpu.exceptions import PlacementGroupUnschedulableError

logger = logging.getLogger(__name__)


@dataclass
class NodeRecord:
    node_id: NodeID
    address: str
    resources_total: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.time)
    node_manager: Any = None  # in-process handle to the Node (single-host runtime)


@dataclass
class ActorRecord:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: str  # PENDING | ALIVE | RESTARTING | DEAD
    node_id: Optional[NodeID] = None
    spec: Optional[TaskSpec] = None
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: Optional[str] = None
    #: seq of the ACTOR_DEAD cluster event, so late submissions to the
    #: dead actor can attach its recovery-incident timeline
    death_event_seq: Optional[int] = None


@dataclass
class JobRecord:
    job_id: JobID
    state: str = "RUNNING"  # RUNNING | SUCCEEDED | FAILED
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]
    node_id: Optional[NodeID] = None
    # per-bundle node-label requirements (reference: bundle_label_selector
    # on placement groups, used by reserve_tpu_slice)
    label_selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class PlacementGroupRecord:
    pg_id: PlacementGroupID
    name: str
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundles: List[Bundle]
    state: str = "PENDING"  # PENDING | CREATED | REMOVED


class KVStore:
    """Namespaced key-value store (reference: gcs_kv_manager.cc,
    python/ray/experimental/internal_kv.py)."""

    def __init__(self, on_change=None):
        self._data: Dict[Tuple[str, bytes], bytes] = {}
        self._lock = threading.Lock()
        # persistence hook: feeds the durability journal when set
        self._on_change = on_change
        # key waiters: blocking/async waits fire on put, replacing the
        # reference's long-poll pattern (and our earlier client-side
        # 2ms polling) with event-driven wakeups
        self._waiters: Dict[Tuple[str, bytes], List] = {}

    def put(self, key: bytes, value: bytes, namespace: str = "") -> None:
        with self._lock:
            self._data[(namespace, key)] = value
            # hook fires under the lock: the journal must record
            # same-key mutations in their in-memory apply order
            if self._on_change is not None:
                self._on_change("put", (namespace, key), value)
            waiters = self._waiters.pop((namespace, key), None)
        for callback in waiters or ():
            try:
                callback(value)
            except Exception:
                # one waiter must not break put() for the others
                logger.exception("kv waiter callback failed for %r", key)

    def add_waiter(self, key: bytes, namespace: str, callback):
        """Register ``callback(value)`` to fire on the next put of the
        key; returns the current value instead if it already exists
        (atomic check-or-register, no missed-wakeup window)."""
        with self._lock:
            value = self._data.get((namespace, key))
            if value is not None:
                return value
            self._waiters.setdefault((namespace, key), []).append(callback)
            return None

    def remove_waiter(self, key: bytes, namespace: str, callback) -> None:
        with self._lock:
            waiters = self._waiters.get((namespace, key))
            if waiters is not None:
                try:
                    waiters.remove(callback)
                except ValueError:
                    pass
                if not waiters:
                    del self._waiters[(namespace, key)]

    def wait(self, key: bytes, namespace: str = "",
             timeout: Optional[float] = None) -> Optional[bytes]:
        """Block until the key exists (or timeout → None)."""
        event = threading.Event()
        slot: List[Optional[bytes]] = [None]

        def callback(value):
            slot[0] = value
            event.set()

        existing = self.add_waiter(key, namespace, callback)
        if existing is not None:
            return existing
        if not event.wait(timeout):
            self.remove_waiter(key, namespace, callback)
            # a put may have fired between wait() expiry and removal
            return slot[0]
        return slot[0]

    def get(self, key: bytes, namespace: str = "") -> Optional[bytes]:
        with self._lock:
            return self._data.get((namespace, key))

    def delete(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            existed = self._data.pop((namespace, key), None) is not None
            if existed and self._on_change is not None:
                self._on_change("del", (namespace, key), None)
        return existed

    def keys(self, prefix: bytes = b"", namespace: str = "") -> List[bytes]:
        with self._lock:
            return [k for (ns, k) in self._data if ns == namespace and k.startswith(prefix)]

    def exists(self, key: bytes, namespace: str = "") -> bool:
        with self._lock:
            return (namespace, key) in self._data


class Pubsub:
    """In-process pub/sub with per-subscriber queues
    (reference: src/ray/pubsub/publisher.h:245)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable[[Any], None]]] = defaultdict(list)

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._lock:
            self._subs[channel].append(callback)

    def unsubscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        with self._lock:
            try:
                self._subs[channel].remove(callback)
            except ValueError:
                pass

    def publish(self, channel: str, message: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                # one subscriber must not break publish for the rest
                logger.exception("pubsub subscriber failed on %r", channel)


class Gcs:
    def __init__(self, store=None):
        """``store``: optional FileStoreClient for control-plane
        durability — the KV store, job records, the function store, and
        NAMED actor records are journaled and replayed on restart
        (reference: Redis-backed GCS + gcs_init_data.cc replay). The
        node table and anonymous actors are not: nodes re-register
        themselves (reporting surviving actor workers for re-binding),
        and anonymous actors die with their driver."""
        self.lock = threading.RLock()
        self.store = store

        def kv_change(op, key, value):
            if op == "put":
                store.put("kv", key, value)
            else:
                store.delete("kv", key)

        self.kv = KVStore(on_change=kv_change if store else None)
        self.pubsub = Pubsub()
        self.nodes: Dict[NodeID, NodeRecord] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.jobs: Dict[JobID, JobRecord] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupRecord] = {}
        self.functions: Dict[str, bytes] = {}  # function/class store
        cfg = get_config()
        self.task_events: deque = deque(maxlen=cfg.task_events_buffer_size)
        # Cluster lifecycle events (core/events.py): bounded like the
        # task-event buffer, appended from every lifecycle transition.
        # Tuple layout (seq, ts, severity, kind, node_id, worker_id,
        # actor_id, task_id, message, caused_by, data); materialized
        # lazily in list_cluster_events.
        self.cluster_events: deque = deque(
            maxlen=cfg.cluster_events_buffer_size)
        self._cluster_event_seq = 0
        # Distributed-trace spans (proxy/router/replica/engine hops and
        # user tracing.span() blocks) — tuple layout (trace_id, span_id,
        # parent_span_id, name, component, t_start, duration, tags).
        # Grouped per trace in an OrderedDict ordered by last-span
        # arrival: append moves the trace to the end, and traces past
        # trace_store_max_traces are LRU-evicted from the front (a
        # loadgen run mints a fresh trace per request — unbounded, the
        # store ate the heap). Spans within one trace are a bounded
        # ring too (trace_store_max_spans).
        self.trace_spans: "OrderedDict[str, deque]" = OrderedDict()
        self._trace_cap = max(1, cfg.trace_store_max_traces)
        self._trace_span_cap = max(16, cfg.trace_store_max_spans)
        if store is not None:
            self._restore_from_store()

    def _restore_from_store(self) -> None:
        """Replay the durability journal into the fresh tables
        (reference: gcs_init_data.cc loading all tables on GCS start).
        Node records are NOT restored — daemons re-register themselves
        within node_reconnect_s; KV, jobs, functions, and named actors
        come back."""
        for key, value in self.store.items("kv").items():
            namespace, k = key
            self.kv._data[(namespace, k)] = value
        for job_id_bin, record in self.store.items("jobs").items():
            self.jobs[JobID(job_id_bin)] = record
        for function_id, blob in self.store.items("functions").items():
            self.functions[function_id] = blob
        # Named actors come back ORPHANED: unreachable until their
        # daemon re-registers and reports them live, at which point the
        # runtime re-binds them and flips the state to ALIVE (head FT
        # slice 2; reference: gcs_init_data.cc actor-table replay).
        for aid_bin, record in self.store.items("actors").items():
            record.state = "ORPHANED"
            record.node_id = None
            self.actors[record.actor_id] = record
            if record.name:
                self.named_actors[(record.namespace, record.name)] = (
                    record.actor_id)
            self.add_cluster_event(
                "ACTOR_ORPHANED", "WARNING", actor_id=record.actor_id,
                message="restored from journal; awaiting node re-report")

    # --- nodes ---------------------------------------------------------
    def register_node(self, record: NodeRecord,
                      publish: bool = True) -> None:
        """``publish=False`` installs the record without the ALIVE
        pubsub push — for callers that must install under a lock (push
        is synchronous and a slow subscriber would wedge them) and
        publish after release."""
        with self.lock:
            self.nodes[record.node_id] = record
        self.add_cluster_event(
            "NODE_ADDED", node_id=record.node_id,
            message=record.address or "in-process node",
            data={"resources": dict(record.resources_total)})
        if publish:
            self.pubsub.publish("node", ("ALIVE", record.node_id))

    def mark_node_dead(self, node_id: NodeID,
                       expected_manager=None) -> Optional[int]:
        """``expected_manager`` pins the call to one node incarnation:
        if a re-registration has already replaced the record (same id,
        new node_manager), the death is stale — skip both the flip and
        the DEAD publish so subscribers never see DEAD after the new
        incarnation's ALIVE.

        Returns the NODE_DEAD cluster-event seq (the incident root the
        reschedule/reconstruction events it triggers chain from via
        ``caused_by``), or None for a stale/disabled call. May run on
        the IO-loop thread (EOF death path) — metrics use the
        ``*_local`` variants."""
        detect_s = None
        with self.lock:
            rec = self.nodes.get(node_id)
            if (expected_manager is not None and rec is not None
                    and rec.node_manager is not expected_manager):
                return None
            if rec:
                rec.alive = False
            # detect latency: last heartbeat seen -> declared dead (only
            # meaningful for heartbeat-monitored remote nodes)
            last_hb = getattr(expected_manager, "last_heartbeat", None)
            if last_hb is not None:
                detect_s = max(0.0, time.time() - last_hb)
        data = {} if detect_s is None else {"detect_s": round(detect_s, 6)}
        # Causal chain preference: an open heartbeat-miss episode is the
        # closest precursor; failing that, an injected chaos fault
        # (devtools/chaos.py stashes its CHAOS_INJECTED seq on the node
        # manager) roots the incident at its deliberate cause.
        cause = getattr(expected_manager, "_hb_miss_seq", None)
        if cause is None:
            cause = getattr(expected_manager, "_chaos_cause_seq", None)
        seq = self.add_cluster_event(
            "NODE_DEAD", "ERROR", node_id=node_id,
            message="node declared dead",
            caused_by=cause,
            data=data)
        events_mod.NODE_DEATHS.inc_local()
        if detect_s is not None:
            events_mod.RECOVERY_SECONDS.observe_local(
                detect_s, tags={"phase": "detect"})
        self.pubsub.publish("node", ("DEAD", node_id))
        return seq

    def alive_nodes(self) -> List[NodeRecord]:
        with self.lock:
            return [n for n in self.nodes.values() if n.alive]

    def heartbeat(self, node_id: NodeID) -> None:
        with self.lock:
            rec = self.nodes.get(node_id)
            if rec:
                rec.last_heartbeat = time.time()

    # --- functions -----------------------------------------------------
    def put_function(self, function_id: str, blob: bytes) -> None:
        with self.lock:
            self.functions[function_id] = blob
        if self.store is not None:
            self.store.put("functions", function_id, blob)

    def get_function(self, function_id: str) -> Optional[bytes]:
        with self.lock:
            return self.functions.get(function_id)

    # --- actors --------------------------------------------------------
    def register_actor(self, record: ActorRecord) -> None:
        superseded = None
        with self.lock:
            if record.name:
                key = (record.namespace, record.name)
                if key in self.named_actors:
                    existing = self.actors.get(self.named_actors[key])
                    if existing and existing.state == "ORPHANED":
                        # Post-head-restart replay whose node has not
                        # (and may never) re-register: the user
                        # re-creating the name supersedes it. Mark the
                        # orphan dead so a late node report won't adopt
                        # it (the runtime kills the stray worker).
                        existing.state = "DEAD"
                        existing.death_cause = "superseded by re-creation"
                        self._persist_actor(existing)
                        superseded = existing.actor_id
                    elif existing and existing.state != "DEAD":
                        raise ValueError(
                            f"actor name {record.name!r} already taken in "
                            f"namespace {record.namespace!r}"
                        )
                self.named_actors[key] = record.actor_id
            self.actors[record.actor_id] = record
            self._persist_actor(record)
        if superseded is not None:
            self.add_cluster_event(
                "ACTOR_DEAD", "WARNING", actor_id=superseded,
                message="orphan superseded by re-creation")
        self.add_cluster_event(
            "ACTOR_CREATED", actor_id=record.actor_id,
            message=record.name or "")

    def _persist_actor(self, record: ActorRecord) -> None:
        """Journal NAMED actors so a restarted head can re-attach them
        to surviving daemon workers (head FT slice 2; reference:
        gcs_actor_manager persistence + gcs_init_data.cc replay).
        Anonymous actors die with their driver, so they are not kept.
        Caller holds self.lock."""
        if self.store is None or not record.name:
            return
        if record.state == "DEAD":
            self.store.delete("actors", record.actor_id.binary())
            return
        try:
            self.store.put("actors", record.actor_id.binary(), record)
        except Exception:  # noqa: BLE001 — an unpicklable creation spec
            # (e.g. closure-captured state) must not break the actor;
            # persist the record without it (re-attach still works, a
            # post-restart RESTART of the actor will not)
            import dataclasses
            self.store.put("actors", record.actor_id.binary(),
                           dataclasses.replace(record, spec=None))

    def update_actor_state(self, actor_id: ActorID, state: str,
                           node_id: Optional[NodeID] = None,
                           death_cause: Optional[str] = None,
                           cause_seq: Optional[int] = None) -> Optional[int]:
        """Transition an actor's lifecycle state. THE event-emitting
        helper for actor ``state`` mutations (graftlint GL018): every
        transition appends an ``ACTOR_<state>`` cluster event, with
        ``cause_seq`` chaining restarts/deaths to the node/worker death
        that triggered them. Returns the event seq (None when the actor
        is unknown or events are disabled) so callers can thread it."""
        with self.lock:
            rec = self.actors.get(actor_id)
            if rec is None:
                return None
            rec.state = state
            if node_id is not None:
                rec.node_id = node_id
            if death_cause is not None:
                rec.death_cause = death_cause
            if state == "DEAD" and rec.name:
                # Release the name so it can be re-created (reference:
                # gcs_actor_manager removes named-actor entries on death).
                # Guarded by actor_id so a late duplicate DEAD transition
                # can't wipe a live successor that reused the name.
                key = (rec.namespace, rec.name)
                if self.named_actors.get(key) == actor_id:
                    del self.named_actors[key]
                    self.kv.delete(rec.name.encode(),
                                   namespace="actor_handles")
            self._persist_actor(rec)
        severity = ("ERROR" if state == "DEAD"
                    else "WARNING" if state == "RESTARTING" else "INFO")
        seq = self.add_cluster_event(
            "ACTOR_" + state, severity, actor_id=actor_id,
            node_id=node_id, message=death_cause or "",
            caused_by=cause_seq)
        if state == "DEAD" and seq is not None:
            with self.lock:
                rec = self.actors.get(actor_id)
                if rec is not None:
                    rec.death_event_seq = seq
        self.pubsub.publish("actor", (state, actor_id))
        return seq

    def get_actor(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self.lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str = "") -> Optional[ActorRecord]:
        with self.lock:
            actor_id = self.named_actors.get((namespace, name))
            return self.actors.get(actor_id) if actor_id else None

    # --- jobs ----------------------------------------------------------
    def register_job(self, record: JobRecord) -> None:
        with self.lock:
            self.jobs[record.job_id] = record
        if self.store is not None:
            self.store.put("jobs", record.job_id.binary(), record)

    # --- placement groups ----------------------------------------------
    def register_placement_group(self, record: PlacementGroupRecord) -> None:
        with self.lock:
            self.placement_groups[record.pg_id] = record

    def get_placement_group(self, pg_id: PlacementGroupID) -> Optional[PlacementGroupRecord]:
        with self.lock:
            return self.placement_groups.get(pg_id)

    def list_placement_groups(self) -> List[PlacementGroupRecord]:
        with self.lock:
            return list(self.placement_groups.values())

    # --- task events (observability) -----------------------------------
    def add_task_event(self, event) -> None:
        """Append one task event — either a TaskEvent or the hot-path
        tuple layout (task_id, name, state, timestamp, node_id,
        worker_id, error, duration, parent_task_id, trace_id). Tuples
        avoid dataclass construction on the submit/complete hot path (3
        events/task; reference batches via task_event_buffer.h:297) and
        are materialized lazily in list_task_events."""
        if get_config().task_events_enabled:
            with self.lock:  # readers list() the deque concurrently
                self.task_events.append(event)

    def add_task_events(self, events) -> None:
        """Batch append (one lock) — see add_task_event for the layout."""
        if get_config().task_events_enabled:
            with self.lock:
                self.task_events.extend(events)

    def list_task_events(self, limit: int = 1000) -> List[TaskEvent]:
        with self.lock:  # appends during iteration raise RuntimeError
            raw = list(self.task_events)[-limit:]
        out: List[TaskEvent] = []
        for ev in raw:
            if type(ev) is tuple:
                (task_id, name, state, ts, node_id, worker_id, error,
                 duration, parent_task_id, trace_id) = ev
                ev = TaskEvent(task_id=task_id, name=name, state=state,
                               node_id=node_id, worker_id=worker_id,
                               error=error, duration=duration,
                               parent_task_id=parent_task_id,
                               trace_id=trace_id)
                ev.timestamp = ts
            out.append(ev)
        return out

    # --- cluster lifecycle events (core/events.py) ----------------------
    def add_cluster_event(self, kind: str, severity: str = "INFO", *,
                          node_id=None, worker_id=None, actor_id=None,
                          task_id=None, message: str = "",
                          caused_by: Optional[int] = None,
                          data: Optional[dict] = None) -> Optional[int]:
        """Append one lifecycle event and return its seq (None when the
        plane is disabled). Hot-path layout mirrors add_task_event: one
        tuple build + deque append under the lock; ids normalized to
        hex strings at emit so readers are allocation-free."""
        if not get_config().cluster_events_enabled:
            return None
        row_tail = (severity, kind, ent_hex(node_id), ent_hex(worker_id),
                    ent_hex(actor_id), ent_hex(task_id), message,
                    caused_by, data or {})
        with self.lock:
            self._cluster_event_seq += 1
            seq = self._cluster_event_seq
            self.cluster_events.append((seq, time.time()) + row_tail)
        return seq

    def list_cluster_events(self, limit: int = 1000, kinds=None,
                            severity: Optional[str] = None,
                            node_id=None, worker_id=None, actor_id=None,
                            task_id=None,
                            since_seq: Optional[int] = None,
                            ) -> List[ClusterEvent]:
        """Chronological tail of the event store, materialized lazily.
        ``kinds`` is an iterable of kind names; ``severity`` a MINIMUM
        level (e.g. "WARNING" keeps WARNING+ERROR); entity filters
        match on hex strings; ``since_seq`` keeps events newer than a
        previously-seen seq (the CLI --follow cursor)."""
        unfiltered = (kinds is None and severity is None and
                      node_id is None and worker_id is None and
                      actor_id is None and task_id is None and
                      since_seq is None)
        with self.lock:
            if unfiltered:
                # The periodic snapshot dump lands here every ~2s: keep
                # only the tail instead of listing the full (up to
                # cluster_events_buffer_size) deque under the lock every
                # emitter contends on.
                raw = list(deque(self.cluster_events, maxlen=limit))
            else:
                raw = list(self.cluster_events)
        if unfiltered:
            return [ClusterEvent.from_tuple(row) for row in raw]
        if since_seq is not None:
            raw = [row for row in raw if row[0] > since_seq]
        if kinds is not None:
            wanted = set(kinds)
            raw = [row for row in raw if row[3] in wanted]
        if severity is not None:
            floor = events_mod.SEVERITIES.index(severity)
            raw = [row for row in raw
                   if events_mod.SEVERITIES.index(row[2]) >= floor]
        for idx, ent in ((4, node_id), (5, worker_id), (6, actor_id),
                         (7, task_id)):
            if ent is not None:
                want = ent_hex(ent)
                raw = [row for row in raw if row[idx] == want]
        return [ClusterEvent.from_tuple(row) for row in raw[-limit:]]

    # --- distributed-trace spans ---------------------------------------
    def add_trace_span(self, span) -> None:
        """Append one finished span: (trace_id, span_id, parent_span_id,
        name, component, t_start, duration, tags). Touching a trace
        moves it to the LRU tail; the coldest trace is evicted once the
        store holds more than trace_store_max_traces traces."""
        if get_config().task_events_enabled:
            with self.lock:
                entry = self.trace_spans.get(span[0])
                if entry is None:
                    entry = deque(maxlen=self._trace_span_cap)
                    self.trace_spans[span[0]] = entry
                else:
                    self.trace_spans.move_to_end(span[0])
                entry.append(span)
                while len(self.trace_spans) > self._trace_cap:
                    self.trace_spans.popitem(last=False)

    def spans_for_trace(self, trace_id: str) -> List[tuple]:
        with self.lock:
            return list(self.trace_spans.get(trace_id, ()))

    def events_for_trace(self, trace_id: str,
                         limit: int = 100_000) -> List[TaskEvent]:
        return [ev for ev in self.list_task_events(limit=limit)
                if ev.trace_id == trace_id]

    def recent_trace_ids(self, limit: int = 100) -> List[str]:
        """Most-recently-touched trace ids, newest first (the
        dashboard's trace index) — the LRU order read backwards."""
        with self.lock:
            ids = list(self.trace_spans)
        return ids[::-1][:limit]
