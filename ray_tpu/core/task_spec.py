"""Task and actor specifications.

Capability parity with the reference's TaskSpec protobuf
(reference: src/ray/protobuf/common.proto TaskSpec; src/ray/common/lease/)
— the unit handed from submitter to scheduler to worker. Arguments are
either inline serialized values or ObjectRefs to be resolved before
dispatch (reference: task_submission/dependency_resolver.h:35).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID


@dataclass
class Arg:
    """One task argument: exactly one of value_bytes / object_id is set."""
    value_bytes: Optional[bytes] = None  # serialization.pack'd inline value
    object_id: Optional[ObjectID] = None


@dataclass
class SchedulingStrategy:
    """Where a task/actor may run.

    reference: python/ray/util/scheduling_strategies.py —
    DEFAULT (hybrid pack/spread), SPREAD, node affinity, node labels,
    placement-group bundles.
    """
    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | NODE_ANTI_AFFINITY | NODE_LABEL | PLACEMENT_GROUP
    node_id: Optional[NodeID] = None
    soft: bool = False
    # label selector: {key: value} exact-match requirements
    labels: Dict[str, str] = field(default_factory=dict)
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    function_id: str                    # key into the GCS function store
    args: List[Arg]
    kwargs: Dict[str, Arg] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    name: str = ""
    owner: str = "driver"               # routing key for completion delivery
    # actor task fields
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    seq_no: int = 0                     # per-caller actor-task ordering
    # actor creation fields
    is_actor_creation: bool = False
    max_restarts: int = 0
    max_concurrency: int = 1
    # user-facing actor name (named actors) — carried in the spec so
    # actors created from clients/workers register under their name at
    # the head (and get journaled for head-restart re-attach)
    actor_name: Optional[str] = None
    # runtime environment (normalized dict; see ray_tpu/runtime_env/) —
    # workers are pooled per (hardware profile, runtime_env_hash)
    runtime_env: Optional[Dict[str, Any]] = None
    runtime_env_hash: str = ""
    # tracing: the task (if any) that submitted this one — drawn as a
    # flow arrow in the timeline (reference: span context in TaskSpec,
    # util/tracing/tracing_helper.py)
    parent_task_id: Optional[TaskID] = None
    # distributed trace context (reference: span context propagated in
    # the task spec, tracing_helper.py): the submitting context's
    # trace_id and span_id travel with the spec so the executing worker
    # re-establishes the trace before user code runs — one trace_id
    # follows a request through serve hops and nested submissions
    trace_id: Optional[str] = None
    parent_span_id: Optional[str] = None

    def return_ids(self) -> List[ObjectID]:
        """Derived return ObjectIDs (cached — callers must not mutate).

        Called several times per task on the submit/complete hot path;
        each derivation is a sha1, so memoize per spec instance.
        """
        cached = self.__dict__.get("_return_ids_cache")
        if cached is None:
            cached = [ObjectID.for_task_return(self.task_id, i)
                      for i in range(self.num_returns)]
            self.__dict__["_return_ids_cache"] = cached
        return cached

    def __getstate__(self):
        # Don't ship the derived-ID cache over the wire: each side
        # re-derives lazily, and specs cross a socket once per dispatch.
        state = dict(self.__dict__)
        state.pop("_return_ids_cache", None)
        return state

    def dependencies(self) -> List[ObjectID]:
        deps = [a.object_id for a in self.args if a.object_id is not None]
        deps += [a.object_id for a in self.kwargs.values() if a.object_id is not None]
        return deps


@dataclass(slots=True)
class TaskEvent:
    """Observability record for one task state transition
    (reference: src/ray/core_worker/task_event_buffer.h:297)."""
    task_id: TaskID
    name: str
    state: str    # PENDING | SCHEDULED | RUNNING | FINISHED | FAILED | PROFILE
    timestamp: float = field(default_factory=time.time)
    node_id: Optional[NodeID] = None
    worker_id: Optional[WorkerID] = None
    error: Optional[str] = None
    # PROFILE spans (user ray_tpu.util.tracing.profile blocks) carry an
    # explicit duration; parent_task_id links nested submissions for
    # timeline flow arrows (reference: ProfileEvent, profile_event.cc +
    # span context propagated in the task spec, tracing_helper.py)
    duration: Optional[float] = None
    parent_task_id: Optional[TaskID] = None
    # distributed trace this task belongs to (None when submitted with
    # no active trace context and task-level root minting disabled)
    trace_id: Optional[str] = None
