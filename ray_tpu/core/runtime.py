"""Driver/head runtime: submission, scheduling loop, ownership, actors.

Capability parity with the reference's core-worker driver role plus the
GCS-side managers (reference: src/ray/core_worker/core_worker.h:170
SubmitTask/Get/Put/Wait; gcs_actor_manager.h:93 actor lifecycle +
restarts; task retry in task_manager.h:175). The head process is the
single owner and scheduler authority: workers reach it over their node
socket, nodes are in-process objects (multi-node simulated clusters run
many Nodes in this one process — reference: python/ray/cluster_utils.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import events as events_mod
from ray_tpu.core import serialization
from ray_tpu.core.config import get_config, reset_config
from ray_tpu.core.gcs import ActorRecord, Gcs, JobRecord, NodeRecord
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.node import Node
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import MemoryStore
from ray_tpu.core.scheduler import ClusterScheduler
from ray_tpu.core import task_phase as _task_phase
from ray_tpu.core.task_manager import ObjectLocation, ReferenceCounter, TaskManager
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.devtools import refsan
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)

_runtime_lock = threading.Lock()
_runtime = None

_SPILL_MISS = object()  # sentinel: spilled payload not readable here


def get_runtime():
    if _runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _runtime


def get_runtime_or_none():
    return _runtime


def set_runtime(rt) -> None:
    global _runtime
    with _runtime_lock:
        _runtime = rt


class StreamState:
    """Owner-side record of a streaming task's yields (reference:
    task_manager.h streaming-generator return bookkeeping)."""

    def __init__(self):
        self.cond = threading.Condition()
        self.items: List[ObjectID] = []
        self.done = False
        self.error: Optional[Exception] = None
        # (index, fire(status, payload)) waiters from worker STREAM_NEXT
        self.waiters: List[Tuple[int, Callable]] = []
        # consumer dropped its generator; late items are reclaimed and
        # the state is popped at stream completion
        self.abandoned = False


class ActorInfo:
    def __init__(self, creation_spec: TaskSpec):
        self.creation_spec = creation_spec
        self.node_id: Optional[NodeID] = None
        self.worker_id: Optional[WorkerID] = None
        self.buffered: deque = deque()
        self.lock = threading.Lock()
        # True only after creation completed AND the buffer was flushed —
        # direct dispatch before that would overtake buffered tasks.
        self.ready_for_dispatch = False
        # Node whose resources the creation task acquired; released exactly
        # once per incarnation at actor death.
        self.resources_node: Optional[NodeID] = None


class DriverRuntime:
    is_driver = True

    def __init__(self, resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None,
                 system_config: Optional[dict] = None,
                 namespace: str = ""):
        reset_config(system_config)
        cfg = get_config()
        store = None
        if cfg.gcs_persistence_path:
            from ray_tpu.core.gcs_store import FileStoreClient
            store = FileStoreClient(cfg.gcs_persistence_path)
        self.gcs = Gcs(store=store)
        # Fresh flight-recorder collector per session; enables the
        # driver's own journal (and the env flags workers inherit)
        # when cfg.flight_recorder_enabled.
        from ray_tpu.util import flight_recorder
        flight_recorder.init_driver()
        # Same idea for the lifetime sanitizer: fresh collector per
        # session, ledger enabled iff RAY_TPU_REFSAN is exported.
        refsan.init_driver()
        # ... and the collective-program sanitizer (RAY_TPU_COLLSAN):
        # fresh fingerprint store per session, stall watchdog started
        # when enabled.
        from ray_tpu.devtools import collsan
        collsan.init_driver()
        # ... and the sampling profiler (RAY_TPU_PROFILER): fresh
        # store per session, driver sampler started when enabled.
        from ray_tpu.devtools import profiler
        profiler.init_driver()
        _task_phase.reset()
        self.scheduler = ClusterScheduler(self.gcs)
        self.task_manager = TaskManager()
        self.reference_counter = ReferenceCounter()
        self.reference_counter.set_deleter(self._maybe_delete_object)
        self.reference_counter.refsan_role = "owner"
        # Hostile-store mode collapses the borrow grace window so
        # deferred reclaims fire at the earliest legal moment — tier-1
        # uses it (with refsan) to force PR-13-shaped races instead of
        # waiting for them.
        self._ref_grace_s = 0.05 if cfg.refsan_hostile_eviction else 2.0
        # objects pinned because they are contained in a stored value
        # (task return / put): container oid -> contained oids
        self._contained_refs: Dict[ObjectID, List[ObjectID]] = {}
        self._contained_lock = threading.Lock()
        # streaming-task yields (reference: _raylet.pyx:299)
        self._streams: Dict[TaskID, StreamState] = {}
        self._streams_lock = threading.Lock()
        # Serializes remote-node install/reap vs death observers so a
        # stale connection's EOF can never tear down a re-registered
        # node (RLock: register's reap path re-enters death). See
        # register_remote_node / on_remote_node_death.
        self._node_reg_lock = threading.RLock()
        # pubsub push routes per worker, removed at death
        self._worker_subs: Dict[tuple, list] = {}
        self._worker_subs_lock = threading.Lock()
        # Lineage: specs of completed stateless tasks, kept (bounded
        # LRU) so lost objects can be reconstructed by re-execution
        # (reference: task_manager.h:175 lineage + max_lineage_bytes;
        # object_recovery_manager.h:41). Actor/streaming tasks are
        # excluded — reconstruction is wrong for stateful work
        # (SURVEY §7).
        from collections import OrderedDict
        self._lineage: "OrderedDict[TaskID, TaskSpec]" = OrderedDict()
        self._lineage_by_object: Dict[ObjectID, TaskID] = {}
        self._lineage_lock = threading.Lock()
        self._reconstructing: set = set()
        # Recovery attribution (core/events.py): death-triggered work
        # carries the death event's seq so incident timelines chain.
        # _cause_by_task: resubmitted task -> (retry_event_seq,
        # death_ts); its next lease grant closes the reschedule phase.
        # _last_death_seq seeds reconstruction chains (lineage recovery
        # has no per-object death attribution); _reconstruct_events
        # tracks open RECONSTRUCT_START spans per requested object.
        self._event_chain_lock = threading.Lock()
        self._cause_by_task: Dict[TaskID, tuple] = {}
        self._last_death_seq: Optional[int] = None
        self._reconstruct_events: Dict[ObjectID, tuple] = {}
        # single expiry thread for deferred ref drops (no Timer churn)
        self._expiry_items: List[tuple] = []
        self._expiry_cv = threading.Condition()
        self._expiry_thread = threading.Thread(
            target=self._expiry_loop, name="ref-expiry", daemon=True)
        self._expiry_thread.start()
        # periodic state snapshot for the out-of-process CLI
        # (reference: the dashboard state aggregator; here a JSON file)
        self._state_dump_thread = threading.Thread(
            target=self._state_dump_loop, name="state-dump", daemon=True)
        self._state_dump_thread.start()
        self.memory_store = MemoryStore()
        self.namespace = namespace
        self.job_id = JobID.from_random()
        self.gcs.register_job(JobRecord(self.job_id))
        self.nodes: Dict[NodeID, Node] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self._put_counter = 0
        self._put_lock = threading.Lock()
        self._driver_task_id = TaskID.from_random()
        self._stopped = threading.Event()
        # Scheduling queue
        self._sched_cond = threading.Condition()
        self._schedulable: deque = deque()
        self._infeasible: List[TaskSpec] = []
        # task ids dispatched by burst grant (lease reuse): they hold
        # no scheduler resources; release paths consume the marker
        self._overcommitted: set = set()
        # snapshot of the scheduling backlog, refreshed each loop pass;
        # read by the autoscaler's demand export (reference:
        # gcs_autoscaler_state_manager.h pending-demand reporting)
        self._backlog_view: List[TaskSpec] = []
        # Placement groups waiting for capacity: creation is queued,
        # not fail-fast — the autoscaler reads these as gang demand and
        # new-node registration retries them (reference:
        # gcs_placement_group_scheduler.h:281 pending queue + 2PC).
        self._pending_pgs: List = []
        self._pg_lock = threading.Lock()
        # Fast-dispatch lease cache: resource-shape -> last node that
        # granted it (reference: owner-side lease caching per resource
        # shape, normal_task_submitter.cc:499). try_acquire on the
        # cached node skips the full pick_node scan on the hot path;
        # a failed acquire falls back and refreshes the entry.
        self._dispatch_cache: Dict[tuple, NodeID] = {}
        self._sched_thread = threading.Thread(
            target=self._scheduling_loop, name="scheduler", daemon=True)
        # objects replicated beyond their primary location by node-to-node
        # transfer: oid -> set of NodeIDs holding a sealed copy
        self._replica_lock = threading.Lock()
        self._object_replicas: Dict[ObjectID, set] = {}
        self.head_node_id = self.add_node(
            resources if resources is not None else None, labels,
            object_store_memory)
        # Multi-host control plane: a TCP listener node daemons register
        # with (reference: gcs_server accepting raylet registrations) and
        # an object server for chunked node-to-node transfer out of the
        # in-process stores. Disabled unless head_port >= 0.
        self.head_server = None
        self.object_server = None
        self.head_address: Optional[str] = None
        cfg = get_config()
        if cfg.head_port >= 0:
            from ray_tpu.core.object_transfer import ObjectServer
            from ray_tpu.core.remote_node import HeadServer
            self.object_server = ObjectServer(self._resolve_local_store,
                                              host=cfg.head_host)
            self.head_server = HeadServer(self, cfg.head_host, cfg.head_port)
            self.head_address = (f"{self.head_server.address[0]}:"
                                 f"{self.head_server.address[1]}")
        # Journal-replayed ORPHANED actors whose node never re-registers
        # must not squat their names forever: reap any still orphaned
        # after the reconnect window (name released, journal entry
        # dropped, get_actor then fails cleanly).
        orphans = [aid for aid, rec in self.gcs.actors.items()
                   if rec.state == "ORPHANED"]
        if orphans:
            grace = max(cfg.node_reconnect_s, 60.0)
            timer = threading.Timer(grace, self._reap_stale_orphans,
                                    args=(orphans,))
            timer.daemon = True
            timer.start()
        self._sched_thread.start()

    def _reap_stale_orphans(self, actor_ids) -> None:
        if self._stopped.is_set():
            return
        for aid in actor_ids:
            rec = self.gcs.get_actor(aid)
            if rec is not None and rec.state == "ORPHANED":
                self.gcs.update_actor_state(
                    aid, "DEAD", death_cause="node never re-registered "
                    "after the head restart")

    # --- cluster membership --------------------------------------------
    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None) -> NodeID:
        import multiprocessing
        if resources is None:
            resources = {}
        resources = dict(resources)
        resources.setdefault("CPU", float(multiprocessing.cpu_count()))
        labels = dict(labels or {})
        # TPU hosts self-describe: chip count, slice gang resources,
        # topology labels (reference: accelerator manager hooks in node
        # registration, _private/accelerators/tpu.py).
        from ray_tpu.accelerators.tpu import TpuAcceleratorManager
        TpuAcceleratorManager.augment_node(resources, labels)
        node_id = NodeID.from_random()
        node = Node(self, node_id, resources, labels,
                    object_store_memory=object_store_memory)
        self.nodes[node_id] = node
        monitor = getattr(self, "_log_monitor", None)
        if monitor is not None:  # tail the new node's worker logs too
            monitor.add_dir(os.path.join(node.session_dir, "logs"))
        self.scheduler.add_node(node_id, resources, labels)
        self.gcs.register_node(NodeRecord(
            node_id=node_id, address=node.socket_path,
            resources_total=resources, labels=dict(labels or {}),
            node_manager=node))
        # New capacity: gang reservations first (a queued PG may claim
        # this node whole), then re-check infeasible + queued work.
        self.retry_pending_placement_groups()
        with self._sched_cond:
            self._schedulable.extend(self._infeasible)
            self._infeasible.clear()
            self._sched_cond.notify_all()
        return node_id

    def register_remote_node(self, conn, msg: dict):
        """A node daemon registered over TCP (reference: raylet
        registration with the GCS, gcs_node_manager.h:47)."""
        from ray_tpu.core.remote_node import RemoteNode
        node_id = NodeID(msg["node_id"])
        resources = dict(msg["resources"])
        labels = dict(msg.get("labels") or {})
        with self._node_reg_lock:
            stale = self.nodes.get(node_id)
            reap_tail = None
            if stale is not None and getattr(stale, "is_remote", False):
                # The daemon re-registered (link blip on a live head)
                # before the old connection's EOF woke its reader. Reap
                # the old record exactly as a death would — the daemon
                # dropped any completions during the outage, so its
                # in-flight specs must be retried — then adopt the new
                # connection. The lock makes reap-then-install atomic
                # against death observers (stale EOF reader, heartbeat
                # monitor), whose identity check then no-ops.
                reap_tail = self._reap_remote_node_locked(node_id, stale)
            node = RemoteNode(self, conn, node_id, resources, labels,
                              tuple(msg["object_addr"]),
                              msg.get("address", ""))
            self.nodes[node_id] = node
            self.scheduler.add_node(node_id, resources, labels)
            # Install the GCS record under the lock so record ownership
            # is ordered with self.nodes ownership (a delayed thread's
            # stale register_node after a newer one would otherwise
            # point the record at a superseded incarnation and suppress
            # its real DEAD forever via the expected_manager guard).
            self.gcs.register_node(NodeRecord(
                node_id=node_id, address=node.address,
                resources_total=resources, labels=labels,
                node_manager=node), publish=False)
        # Publishes and spec retries run OUTSIDE the lock (pubsub push
        # is synchronous; a slow subscriber must not wedge the node
        # control plane). The reap tail's DEAD-publish self-suppresses
        # (expected_manager) now that the new record is installed, so
        # subscribers see a plain ALIVE refresh for the re-taken id.
        if reap_tail is not None:
            reap_tail()
        self.gcs.pubsub.publish("node", ("ALIVE", node_id))
        self._adopt_surviving_actors(node, msg.get("actors") or ())
        self.retry_pending_placement_groups()
        with self._sched_cond:
            self._schedulable.extend(self._infeasible)
            self._infeasible.clear()
            self._sched_cond.notify_all()
        return node

    def _adopt_surviving_actors(self, node, reported) -> None:
        """Re-bind actors that survived a head restart on this node's
        workers (head FT slice 2). The daemon reports (actor_id,
        worker_id) pairs in NODE_REGISTER; any pair matching a
        journal-replayed named-actor record becomes a live ActorInfo
        again, so get_actor(name) handles dispatch straight to the
        existing worker (reference: gcs_init_data.cc actor replay +
        workers reconnecting to a restarted GCS)."""
        for aid_bin, wid_bin in reported:
            aid = ActorID(aid_bin)
            record = self.gcs.get_actor(aid)
            if record is None or record.state != "ORPHANED":
                if record is None or record.state == "DEAD":
                    # Stray: anonymous leftover, or an orphan the user
                    # superseded/we reaped — reclaim the worker.
                    node.kill_worker(WorkerID(wid_bin))
                continue
            if aid in self.actors:
                continue  # already tracked (duplicate re-register)
            info = ActorInfo(record.spec)
            info.node_id = node.node_id
            info.worker_id = WorkerID(wid_bin)
            info.ready_for_dispatch = True
            # Re-debit the creation resources so the fresh ledger
            # reflects the worker the actor still occupies.
            if record.spec is not None and self.scheduler.try_acquire(
                    node.node_id, self._spec_resources(record.spec),
                    token=record.spec.task_id):
                info.resources_node = node.node_id
            self.actors[aid] = info
            self.gcs.update_actor_state(aid, "ALIVE",
                                        node_id=node.node_id)

    def on_remote_node_death(self, node_id: NodeID,
                             expected=None) -> None:
        """A remote node's daemon stopped heartbeating or its connection
        dropped. Retry/fail its in-flight work exactly as worker crashes
        would, and promote object replicas where copies survive
        (reference: node death notifications in node_manager.proto +
        gcs_health_check_manager.h:45). ``expected`` pins the call to a
        specific RemoteNode object: if the id has since been re-taken by
        a re-registration, the call no-ops instead of tearing down the
        fresh node (lookup + reap are atomic under _node_reg_lock)."""
        if self._stopped.is_set():
            return
        with self._node_reg_lock:
            tail = self._reap_remote_node_locked(node_id, expected)
        if tail is not None:
            tail()

    def _reap_remote_node_locked(self, node_id: NodeID, expected):
        """In-memory surgery for a remote node's death. Caller holds
        _node_reg_lock. Returns None if the death is stale (id re-taken,
        or another thread won mark_dead), else a closure with the
        publish/retry tail that the caller MUST run after releasing the
        lock — pubsub push is synchronous, so a slow subscriber under
        the lock would wedge registrations, heartbeat monitoring, and
        every daemon EOF reader at once."""
        node = self.nodes.get(node_id)
        if node is None or not getattr(node, "is_remote", False):
            return None
        if expected is not None and node is not expected:
            return None  # superseded: a newer registration owns this id
        if not node.mark_dead():
            return None  # another thread (EOF reader vs monitor) won
        self.nodes.pop(node_id, None)
        self.scheduler.remove_node(node_id)
        self._drop_worker_subscriptions(node_id)
        # Every by-id sweep stays under the lock: past it, a concurrent
        # re-registration may have re-taken this id, and these would
        # clobber the NEW node's records (drop its live replicas, kill
        # its healthy actors). Replica bookkeeping: drop copies on the
        # dead node; objects whose primary lived there survive if any
        # replica exists.
        promote: List[Tuple[ObjectID, NodeID]] = []
        with self._replica_lock:
            for oid, reps in self._object_replicas.items():
                reps.discard(node_id)
                loc = self.task_manager.get_location(oid)
                if (reps and loc is not None and loc.kind == "shm"
                        and loc.node_id == node_id):
                    promote.append((oid, next(iter(reps))))
        # Snapshot the dead incarnation's actors under the lock; the
        # per-actor death handling runs after release (it reschedules
        # via _sched_cond) on this frozen, correctly-attributed set.
        actor_ids = {aid for aid, info in self.actors.items()
                     if info.node_id == node_id}

        def tail():
            # expected_manager keeps a late tail (death thread paused
            # past the lock) from marking a re-registered record dead.
            death_seq = self.gcs.mark_node_dead(node_id,
                                                expected_manager=node)
            if death_seq is not None:
                self._last_death_seq = death_seq
            node.close()
            for oid, new_primary in promote:
                self.task_manager.set_location(
                    oid, ObjectLocation("shm", new_primary))
            # In-flight tasks the daemon can no longer report on.
            self.reap_node_specs(node, node.take_inflight(), actor_ids,
                                 death_seq=death_seq)
            self._handle_pg_node_death(node_id, death_seq)

        return tail

    def reap_node_specs(self, node, specs, actor_ids=None,
                        death_seq=None) -> None:
        """Retry-or-fail specs stranded on a dead RemoteNode object.

        Called from the death harvest above, and from RemoteNode.dispatch
        for the late-track race: a dispatch that tracked its spec AFTER
        the harvest ran (scheduler read the node just before death) must
        reap its own leftovers or the spec hangs forever."""
        actor_ids = set(actor_ids or ())
        for spec in specs:
            # the node's whole resource accounting vanished with
            # remove_node — but a burst-grant marker left behind would
            # misfire on this spec's RETRY (normally-acquired resources
            # skipped at release → permanent capacity leak)
            self._consume_overcommit(spec.task_id)
            if spec.is_actor_creation:
                actor_ids.add(spec.actor_id)
                continue
            retry = (None if spec.num_returns == -1
                     else self.task_manager.consume_retry(spec.task_id))
            if retry is not None:
                self._emit_task_retry(retry, death_seq)
                self._resubmit(retry)
                continue
            err: Exception = WorkerCrashedError(
                f"node {node.node_id.hex()[:8]} died while running "
                f"{spec.name or spec.function_id}")
            if spec.actor_id is not None:
                err = ActorUnavailableError(spec.actor_id, str(err))
            self._record_event(spec, "FAILED", node_id=node.node_id,
                               error=str(err))
            self._fail_task(spec, err)
        for aid in actor_ids:
            self._handle_actor_death(aid, node, cause_seq=death_seq)
        self._signal_scheduler()

    def _emit_task_retry(self, spec: TaskSpec,
                         cause_seq: Optional[int]) -> None:
        """Chain a death-triggered resubmit into its incident: the
        TASK_RETRY event hangs off the death event, and the next lease
        grant for this task id closes the reschedule phase (see
        _emit_lease_grant). Runs on node reader / monitor threads."""
        seq = self.gcs.add_cluster_event(
            "TASK_RETRY", "WARNING", task_id=spec.task_id,
            message=spec.name or str(spec.function_id),
            caused_by=cause_seq)
        if seq is not None:
            with self._event_chain_lock:
                self._cause_by_task[spec.task_id] = (seq, time.time())

    def _emit_lease_grant(self, spec: TaskSpec, node_id: NodeID) -> None:
        """Cluster-event mirror of the SCHEDULED task event. Routine
        grants are DEBUG-severity noise; a grant rescheduling a
        death-triggered retry chains to its TASK_RETRY event and
        observes the incident's reschedule latency (*_local: reachable
        from node reader threads / the IO loop via reap paths)."""
        with self._event_chain_lock:
            cause = self._cause_by_task.pop(spec.task_id, None)
        if cause is None:
            self.gcs.add_cluster_event(
                "LEASE_GRANTED", "DEBUG", node_id=node_id,
                task_id=spec.task_id, message=spec.name or "")
            return
        retry_seq, death_ts = cause
        reschedule_s = max(0.0, time.time() - death_ts)
        self.gcs.add_cluster_event(
            "LEASE_GRANTED", node_id=node_id, task_id=spec.task_id,
            message=spec.name or "", caused_by=retry_seq,
            data={"reschedule_s": round(reschedule_s, 6)})
        events_mod.RECOVERY_SECONDS.observe_local(
            reschedule_s, tags={"phase": "reschedule"})

    def add_object_replica(self, oid: ObjectID, node_id: NodeID) -> None:
        with self._replica_lock:
            self._object_replicas.setdefault(oid, set()).add(node_id)

    def object_holders(self, oid: ObjectID) -> List[NodeID]:
        """Nodes holding a sealed copy (primary first, then replicas)."""
        holders: List[NodeID] = []
        loc = self.task_manager.get_location(oid)
        if loc is not None and loc.kind == "shm" and loc.node_id is not None:
            holders.append(loc.node_id)
        with self._replica_lock:
            for nid in self._object_replicas.get(oid, ()):
                if nid not in holders:
                    holders.append(nid)
        return [nid for nid in holders if nid in self.nodes]

    def _resolve_local_store(self, oid: ObjectID):
        """ObjectServer callback: find an in-process store (or local
        spill file) holding oid — the head serves all its simulated
        nodes from one server."""
        for nid in self.object_holders(oid):
            node = self.nodes.get(nid)
            if (node is not None and not getattr(node, "is_remote", False)
                    and node.store.contains(oid)):
                return node.store
        loc = self.task_manager.get_location(oid)
        if (loc is not None and loc.kind == "spilled" and loc.path
                and os.path.exists(loc.path)):
            return ("file", loc.path)
        return None

    def remove_node(self, node_id: NodeID) -> None:
        """Simulate node failure (chaos testing). In-flight work is
        retried or failed exactly as if each worker crashed
        (reference: node death notifications, node_manager.proto)."""
        existing = self.nodes.get(node_id)
        if existing is not None and getattr(existing, "is_remote", False):
            existing.send({"kind": "STOP"})
            self.on_remote_node_death(node_id, expected=existing)
            return
        node = self.nodes.pop(node_id, None)
        if node is None:
            return
        self.scheduler.remove_node(node_id)
        death_seq = self.gcs.mark_node_dead(node_id)
        if death_seq is not None:
            self._last_death_seq = death_seq
        from ray_tpu.core.node import ACTOR as ACTOR_STATE
        with node._lock:
            casualties = [
                (w, list(w.running.values()),
                 w.actor_id if w.state == ACTOR_STATE else None)
                for w in node._workers.values()
            ]
            queued = [s for q in node._dispatch_queue.values() for s in q]
        node.stop()
        for worker, running, actor_id in casualties:
            if running or actor_id is not None:
                # chain each worker's exit event to the node death
                worker._exit_cause_seq = death_seq
                self.on_worker_crashed(node, worker, running, actor_id)
        # Tasks queued but never started are rescheduled without consuming
        # a retry (the lease was never granted).
        for spec in queued:
            if not self._consume_overcommit(spec.task_id):
                self.scheduler.release(node_id,
                                       self._spec_resources(spec),
                                       token=spec.task_id)
            self._enqueue(spec)
        self._handle_pg_node_death(node_id, death_seq)

    # --- streaming generators -------------------------------------------
    # reference: _raylet.pyx:299 ObjectRefGenerator owner-side protocol.
    def _stream(self, task_id: TaskID) -> StreamState:
        with self._streams_lock:
            state = self._streams.get(task_id)
            if state is None:
                state = self._streams[task_id] = StreamState()
            return state

    def on_stream_item(self, node, msg: dict) -> None:
        """A worker yielded one item of a streaming task."""
        oid = ObjectID(msg["object_id"])
        self._pin_contained(oid, msg.get("contained", ()))
        if msg["item_kind"] == "inline":
            self.memory_store.put(oid, ("packed", bytes(msg["data"])))
            self.task_manager.set_location_and_ready(
                oid, ObjectLocation("memory"))
        else:
            self.task_manager.set_location_and_ready(
                oid, ObjectLocation("shm", node.node_id))
        state = self._stream(TaskID(msg["task_id"]))
        with state.cond:
            abandoned = state.abandoned
            state.items.append(oid)
            fired = [w for w in state.waiters if w[0] < len(state.items)]
            state.waiters = [w for w in state.waiters
                             if w[0] >= len(state.items)]
            state.cond.notify_all()
        if abandoned:
            # nobody will consume this item; reclaim after grace
            self.reference_counter.delete_if_unreferenced(
                oid, defer=(self._ref_grace_s, self._schedule_expiry))
            return
        for index, fire in fired:
            fire("item", state.items[index].binary())

    def _finish_stream(self, task_id: TaskID,
                       error: Optional[Exception]) -> None:
        with self._streams_lock:
            state = self._streams.get(task_id)
        if state is None:
            return
        with state.cond:
            state.done = True
            state.error = error
            waiters = state.waiters
            state.waiters = []
            abandoned = state.abandoned
            state.cond.notify_all()
        if abandoned:
            with self._streams_lock:
                self._streams.pop(task_id, None)
        for index, fire in waiters:
            if index < len(state.items):
                fire("item", state.items[index].binary())
            elif error is not None:
                fire("error", serialization.dumps(error))
            else:
                fire("done", None)

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: Optional[float]):
        """Blocking owner-side wait for stream item ``index``.
        Returns ("item", ObjectID) | ("done", None) | ("error", exc)."""
        state = self._stream(task_id)
        deadline = None if timeout is None else time.monotonic() + timeout
        with state.cond:
            while True:
                if index < len(state.items):
                    return "item", state.items[index]
                if state.done:
                    if state.error is not None:
                        return "error", state.error
                    return "done", None
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"stream item {index} of task {task_id} timed out")
                state.cond.wait(remaining if remaining is not None else 0.5)

    def handle_stream_next(self, worker, msg: dict) -> None:
        """STREAM_NEXT from a worker: reply when the item exists
        (asynchronously if it doesn't yet)."""
        task_id = TaskID(msg["task_id"])
        index = msg["index"]
        req_id = msg.get("req_id")

        def fire(status: str, payload) -> None:
            out = {"kind": "STREAM_REPLY", "req_id": req_id,
                   "status": status}
            if status == "item":
                out["object_id"] = payload
            elif status == "error":
                out["error"] = payload
            worker.send(out)

        state = self._stream(task_id)
        with state.cond:
            if index < len(state.items):
                item = state.items[index].binary()
            elif state.done:
                if state.error is not None:
                    fire("error", serialization.dumps(state.error))
                else:
                    fire("done", None)
                # A worker consumer reached the end; its (handed-off)
                # generator never calls release_stream, so reclaim the
                # state here after a grace window.
                self._schedule_expiry(
                    self._ref_grace_s,
                    lambda: self._pop_finished_stream(task_id))
                return
            else:
                state.waiters.append((index, fire))
                return
        fire("item", item)

    def _pop_finished_stream(self, task_id: TaskID) -> None:
        with self._streams_lock:
            state = self._streams.get(task_id)
            if state is not None and state.done:
                self._streams.pop(task_id, None)

    def release_stream(self, task_id: TaskID, from_index: int) -> None:
        """The consumer dropped its generator: reclaim unconsumed items
        and the StreamState (immediately if the stream finished, else at
        stream completion via the abandoned flag)."""
        with self._streams_lock:
            state = self._streams.get(task_id)
        if state is None:
            return
        with state.cond:
            tail = state.items[from_index:]
            finished = state.done
            state.abandoned = True
        for oid in tail:
            self.reference_counter.delete_if_unreferenced(
                oid, defer=(self._ref_grace_s, self._schedule_expiry))
        if finished:
            with self._streams_lock:
                self._streams.pop(task_id, None)

    # --- lineage reconstruction -----------------------------------------
    def _record_lineage(self, spec: TaskSpec) -> None:
        if (spec.actor_id is not None or spec.is_actor_creation
                or spec.num_returns == -1):
            return
        cfg = get_config()
        if cfg.lineage_max_entries <= 0:
            return
        with self._lineage_lock:
            self._lineage[spec.task_id] = spec
            self._lineage.move_to_end(spec.task_id)
            for oid in spec.return_ids():
                self._lineage_by_object[oid] = spec.task_id
            while len(self._lineage) > cfg.lineage_max_entries:
                old_id, old_spec = self._lineage.popitem(last=False)
                for oid in old_spec.return_ids():
                    if self._lineage_by_object.get(oid) == old_id:
                        del self._lineage_by_object[oid]

    def _lineage_knows(self, oid: ObjectID) -> bool:
        with self._lineage_lock:
            task_id = self._lineage_by_object.get(oid)
            return task_id is not None and task_id in self._lineage

    def _reconstruct_after_infra_failure(self, oid: ObjectID,
                                         err: Exception) -> bool:
        """An object failed due to infrastructure loss (worker/node
        death, not user code): if lineage knows the producer, clear the
        error and re-execute — a reconstruction racing a dying node must
        not poison the object permanently."""
        if not isinstance(err, (WorkerCrashedError, ObjectLostError)):
            return False
        if not self._lineage_knows(oid):
            return False
        self.task_manager.mark_object_unready(oid)
        return self.try_reconstruct(oid)

    def _object_available(self, oid: ObjectID) -> bool:
        if self.memory_store.contains(oid):
            return True
        if self.object_holders(oid):
            return True
        loc = self.task_manager.get_location(oid)
        return loc is not None and loc.kind == "spilled"

    def try_reconstruct(self, oid: ObjectID) -> bool:
        """Re-execute the lost object's producing task (and transitively
        any lost dependencies). Returns True if reconstruction is in
        flight — the caller should wait on readiness again (reference:
        ObjectRecoveryManager::RecoverObject)."""
        with self._lineage_lock:
            if oid in self._reconstructing:
                return True
            task_id = self._lineage_by_object.get(oid)
            root = self._lineage.get(task_id) if task_id else None
            if root is None:
                return False
            # Claim under the same lock as the membership check so a
            # concurrent getter can't resubmit the same producer twice.
            self._reconstructing.add(oid)
        start_seq = self.gcs.add_cluster_event(
            "RECONSTRUCT_START", "WARNING",
            message=f"object {oid.hex()[:12]} lost; re-executing lineage",
            caused_by=self._last_death_seq, data={"oid": oid.hex()})
        if start_seq is not None:
            with self._event_chain_lock:
                self._reconstruct_events[oid] = (start_seq, time.time())
        # Collect the transitive set of lost producers.
        to_resubmit: List[TaskSpec] = []
        stack = [root]
        seen = {root.task_id}
        while stack:
            spec = stack.pop()
            to_resubmit.append(spec)
            for dep in spec.dependencies():
                if self._object_available(dep):
                    continue
                with self._lineage_lock:
                    dep_task = self._lineage_by_object.get(dep)
                    dep_spec = (self._lineage.get(dep_task)
                                if dep_task else None)
                if dep_spec is None:
                    self._reconstruction_done(oid)  # drop the claim
                    return False  # an input is unreconstructible
                if dep_spec.task_id not in seen:
                    seen.add(dep_spec.task_id)
                    stack.append(dep_spec)
        # Mark every output unready first so dep-waiting across the
        # resubmitted set blocks correctly, then resubmit.
        with self._lineage_lock:
            for spec in to_resubmit:
                for out in spec.return_ids():
                    self._reconstructing.add(out)
        for spec in to_resubmit:
            for out in spec.return_ids():
                self.task_manager.mark_object_unready(out)
        for spec in to_resubmit:
            self.task_manager.add_pending(spec)
            self._record_event(spec, "RECONSTRUCTING")
            self._resubmit(spec)
        return True

    def _reconstruction_done(self, oid: ObjectID) -> None:
        with self._lineage_lock:
            self._reconstructing.discard(oid)
        with self._event_chain_lock:
            start = self._reconstruct_events.pop(oid, None)
        if start is None:
            return  # not a tracked span (transitive output / no events)
        start_seq, t0 = start
        reconstruct_s = max(0.0, time.time() - t0)
        self.gcs.add_cluster_event(
            "RECONSTRUCT_DONE",
            message=f"object {oid.hex()[:12]} reconstruction finished",
            caused_by=start_seq,
            data={"reconstruct_s": round(reconstruct_s, 6),
                  "oid": oid.hex()})
        events_mod.RECONSTRUCTIONS.inc_local()
        events_mod.RECOVERY_SECONDS.observe_local(
            reconstruct_s, tags={"phase": "reconstruct"})

    # --- submission ----------------------------------------------------
    def submit_spec(self, spec: TaskSpec) -> None:
        if spec.is_actor_creation and spec.actor_id not in self.actors:
            # Actor created from inside a worker: register here (the head
            # owns actor lifecycle, reference: gcs_actor_manager.h:93).
            self.create_actor(spec)
            return
        self.task_manager.add_pending(spec)
        if spec.actor_id is not None and not spec.is_actor_creation:
            self._record_event(spec, "PENDING")
            self._route_actor_task(spec)
            return
        deps = [d for d in spec.dependencies()
                if not self.task_manager.is_ready(d)]
        if not deps:
            # Direct dispatch on the submitting thread when capacity is
            # free (reference: owner-to-worker direct push with cached
            # leases, normal_task_submitter.cc:499 — the scheduler
            # thread only handles contention/backlog). Two thread hops
            # fewer per task on the hot path. The PENDING event is
            # elided on this path (SCHEDULED subsumes it — reference
            # samples task events too, task_event_buffer.h:297).
            if self._try_fast_dispatch(spec):
                return
            self._record_event(spec, "PENDING")
            self._enqueue(spec)
            return
        self._record_event(spec, "PENDING")
        remaining = [len(deps)]
        lock = threading.Lock()

        def on_dep_ready():
            with lock:
                remaining[0] -= 1
                if remaining[0] != 0:
                    return
            self._enqueue(spec)

        for dep in deps:
            self.task_manager.on_ready(dep, on_dep_ready)

    def _try_fast_dispatch(self, spec: TaskSpec) -> bool:
        if self._schedulable or self._backlog_view:
            return False  # don't jump ahead of parked work
        strategy = spec.strategy
        cache_key = None
        node_id = None
        if strategy.kind == "DEFAULT" and not strategy.labels:
            cache_key = tuple(sorted(spec.resources.items()))
            cached = self._dispatch_cache.get(cache_key)
            if cached is not None and self.scheduler.try_acquire(
                    cached, spec.resources, token=spec.task_id):
                node_id = cached
        if node_id is None:
            try:
                node_id = self.scheduler.pick_node(
                    spec, preferred=self.head_node_id)
            except ValueError:
                return False  # infeasible: let the slow path park it
            if node_id is None or not self.scheduler.try_acquire(
                    node_id, self._spec_resources(spec),
                    token=spec.task_id):
                if cache_key is not None:
                    # scheduler-thread-only state; see __init__ comment
                    self._dispatch_cache.pop(  # graftlint: disable=GL001
                        cache_key, None)
                return False
            if cache_key is not None:
                # scheduler-thread-only state; see __init__ comment
                self._dispatch_cache[cache_key] = node_id  # graftlint: disable=GL001
        node = self.nodes.get(node_id)
        if node is None:
            self.scheduler.release(node_id, self._spec_resources(spec),
                                   token=spec.task_id)
            return False
        if spec.is_actor_creation:
            info = self.actors.get(spec.actor_id)
            if info is not None:
                info.resources_node = node_id
        if _task_phase._TRACKED:
            _task_phase.mark(spec.task_id, "scheduler-queue")
        self.task_manager.mark_dispatched(spec.task_id, node_id)
        self._record_event(spec, "SCHEDULED", node_id=node_id)
        self._emit_lease_grant(spec, node_id)
        node.dispatch(spec)
        return True

    def _enqueue(self, spec: TaskSpec) -> None:
        with self._sched_cond:
            was_empty = not self._schedulable
            self._schedulable.append(spec)
            if was_empty:
                # The scheduler drains the whole list per pass; notifying
                # on every append would wake it once per task.
                self._sched_cond.notify_all()

    def _scheduling_loop(self) -> None:
        backlog: deque = deque()
        self._backlog_blocked = False
        while not self._stopped.is_set():
            # Task completions free resources without a node-join event:
            # give queued gangs a shot each pass (no-op when none wait).
            self.retry_pending_placement_groups()
            with self._sched_cond:
                while not self._schedulable and not backlog and not self._stopped.is_set():
                    self._sched_cond.wait(timeout=0.2)
                    if self._pending_pgs:
                        break  # idle pass: retry pending gangs above
                if self._stopped.is_set():
                    return
                work = list(self._schedulable)
                self._schedulable.clear()
            backlog.extend(work)
            made_progress = False
            # Per-pass memo: once a resource signature fails to place,
            # every identical request this pass fails too (availability
            # only shrinks within a pass) — without this, a deep
            # backlog pays O(backlog) pick_node scans per completion
            # and throughput collapses with queue depth (reference:
            # owner-side lease caching per resource shape, SURVEY §3.2).
            blocked_sigs: set = set()
            for _ in range(len(backlog)):
                if not backlog:
                    break  # burst grants drained ahead of this count
                spec = backlog.popleft()
                task = self.task_manager.get_pending(spec.task_id)
                if task is None:
                    continue  # cancelled/failed meanwhile
                strategy = spec.strategy
                sig = (strategy.kind,
                       strategy.node_id,
                       strategy.soft,  # soft affinity falls through to
                       # the general policy — distinct placement from hard
                       tuple(sorted(strategy.labels.items())),
                       strategy.placement_group_id,
                       strategy.bundle_index,
                       tuple(sorted(spec.resources.items())))
                if sig in blocked_sigs:
                    backlog.append(spec)
                    continue
                try:
                    node_id = self.scheduler.pick_node(
                        spec, preferred=self.head_node_id)
                except ValueError:
                    with self._sched_cond:  # add_node drains this list
                        self._infeasible.append(spec)
                    continue
                if node_id is None or not self.scheduler.try_acquire(
                        node_id, self._spec_resources(spec),
                        token=spec.task_id):
                    blocked_sigs.add(sig)
                    backlog.append(spec)
                    continue
                if spec.is_actor_creation:
                    info = self.actors.get(spec.actor_id)
                    if info is not None:
                        info.resources_node = node_id
                node = self.nodes.get(node_id)
                if node is None:
                    # Node died between pick and dispatch (remote-node
                    # heartbeat monitor removes nodes concurrently).
                    backlog.append(spec)
                    continue
                if _task_phase._TRACKED:
                    _task_phase.mark(spec.task_id, "scheduler-queue")
                self.task_manager.mark_dispatched(spec.task_id, node_id)
                self._record_event(spec, "SCHEDULED", node_id=node_id)
                self._emit_lease_grant(spec, node_id)
                node.dispatch(spec)
                made_progress = True
                # Burst grant (reference: owner-side lease reuse,
                # SURVEY §3.2): ride this acquisition with follow-up
                # same-shape plain-CPU specs from the queue head —
                # the node's worker cap enforces REAL concurrency, so
                # per-task scheduler round trips stop being the
                # throughput ceiling for homogeneous task floods.
                if (strategy.kind == "DEFAULT"
                        and not spec.is_actor_creation
                        and spec.resources == {"CPU": 1.0}):
                    # Head-of-line guard: a deep burst onto a saturated
                    # node only hurts when ANOTHER node has free CPU
                    # (long tasks would pin here while it idles). With
                    # nowhere else to run, burst deep — queued is
                    # queued, and node-side pipelining is the win.
                    budget = get_config().scheduler_burst_grant
                    free_here = self.scheduler.available(node_id).get(
                        "CPU", 0.0)
                    if free_here < 1.0:
                        for other_id, res in (
                                self.scheduler.snapshot().items()):
                            if (other_id != node_id
                                    and res.available.get("CPU", 0.0)
                                    >= 1.0):
                                budget = min(budget, 4)
                                break
                    while budget > 0 and backlog:
                        follower = backlog[0]
                        fs = follower.strategy
                        if (follower.is_actor_creation
                                or fs.kind != "DEFAULT"
                                or follower.resources != {"CPU": 1.0}):
                            break
                        backlog.popleft()
                        if self.task_manager.get_pending(
                                follower.task_id) is None:
                            continue  # cancelled while queued
                        if self.nodes.get(node_id) is not node:
                            # node removed mid-burst: a dispatch onto
                            # the stale object would strand the spec
                            # (the death harvest already ran)
                            backlog.appendleft(follower)
                            break
                        self._overcommitted.add(  # graftlint: disable=GL001
                            follower.task_id)  # GIL-atomic; see _consume_overcommit
                        if _task_phase._TRACKED:
                            _task_phase.mark(follower.task_id,
                                             "scheduler-queue")
                        self.task_manager.mark_dispatched(
                            follower.task_id, node_id)
                        self._record_event(follower, "SCHEDULED",
                                           node_id=node_id)
                        self._emit_lease_grant(follower, node_id)
                        node.dispatch(follower)
                        budget -= 1
            self._backlog_view = list(backlog)
            from ray_tpu.core.scheduler import (INFEASIBLE_TASKS,
                                                QUEUE_DEPTH)
            QUEUE_DEPTH.set(float(len(backlog)))
            INFEASIBLE_TASKS.set(float(len(self._infeasible)))
            if backlog and not made_progress:
                # All blocked on capacity; wait for a release/completion
                # (completions only notify while this flag is up, so the
                # hot path pays no wakeup per task when nothing waits).
                with self._sched_cond:
                    self._backlog_blocked = True
                    self._sched_cond.wait(timeout=0.05)
                    self._backlog_blocked = False

    def resource_demand(self) -> List[Dict[str, float]]:
        """Unmet resource requests: backlog (feasible but waiting on
        capacity) + infeasible tasks. The autoscaler's input (reference:
        gcs_autoscaler_state_manager.h:41 demand export)."""
        with self._sched_cond:
            infeasible = list(self._infeasible)
        specs = self._backlog_view + infeasible
        return [dict(self._spec_resources(s)) for s in specs
                if s.resources]

    # --- pending placement groups --------------------------------------
    # All PENDING<->CREATED<->REMOVED transitions happen under
    # self._pg_lock (lock order: _pg_lock before scheduler lock), so a
    # concurrent retry can never reserve a record another thread is
    # removing (reference: GcsPlacementGroupManager serializes these on
    # the GCS main loop).

    def queue_pending_placement_group(self, record) -> None:
        """Park an unplaceable PG until capacity appears (reference:
        gcs_placement_group_scheduler.h:281 pending queue)."""
        with self._pg_lock:
            record.state = "PENDING"
            self._pending_pgs.append(record)

    def retry_pending_placement_groups(self) -> None:
        """Attempt reservation of every queued PG; called when capacity
        changes (node joins, PG removed, scheduler pass with pending
        gangs). Success flips the GCS record to CREATED, which unblocks
        PlacementGroup.ready() waiters."""
        from ray_tpu.exceptions import PlacementGroupUnschedulableError
        if not self._pending_pgs:  # unlocked peek: usually empty
            return
        with self._pg_lock:
            remaining = []
            progressed = False
            for record in self._pending_pgs:
                if record.state != "PENDING":
                    continue
                try:
                    self.scheduler.reserve_placement_group(record)
                    progressed = True
                except PlacementGroupUnschedulableError:
                    remaining.append(record)
            self._pending_pgs = remaining
        if progressed:
            # Fresh pg-scoped resources may unpark gang tasks that went
            # infeasible while the group was re-pending (node death
            # stripped its custom resources from every ledger).
            with self._sched_cond:
                self._schedulable.extend(self._infeasible)
                self._infeasible.clear()
                self._sched_cond.notify_all()

    def remove_placement_group_record(self, record) -> None:
        """Release or cancel a PG in any state (idempotent)."""
        released = False
        with self._pg_lock:
            if record.state == "CREATED":
                self.scheduler.return_placement_group(record)
                released = True
            elif record.state == "PENDING":
                if record in self._pending_pgs:
                    self._pending_pgs.remove(record)
                record.state = "REMOVED"
        if released:
            # Freed capacity may satisfy a queued gang.
            self.retry_pending_placement_groups()

    def _handle_pg_node_death(self, node_id: NodeID,
                              death_seq: Optional[int] = None) -> None:
        """A gang lost a member node: release its reservation exactly
        once and re-queue it for placement (reference:
        GcsPlacementGroupManager::OnNodeDead rescheduling). Runs in the
        death tail outside _node_reg_lock. _pg_lock orders it against
        user removes; the CREATED check plus return_placement_group's
        REMOVED guard make a racing remove release the bundles exactly
        once. Survivor bundles are credited back here — the dead node's
        ledger is already gone (scheduler.remove_node), so its bundle
        release is a no-op rather than a double credit."""
        hit = []
        with self._pg_lock:
            for record in self.gcs.list_placement_groups():
                if record.state != "CREATED":
                    continue
                if not any(b.node_id == node_id for b in record.bundles):
                    continue
                self.scheduler.return_placement_group(record)
                record.state = "PENDING"
                if record not in self._pending_pgs:
                    self._pending_pgs.append(record)
                hit.append(record)
        for record in hit:
            self.gcs.add_cluster_event(
                "PG_RESCHEDULED", "WARNING", node_id=node_id,
                caused_by=death_seq,
                message=f"placement group {record.pg_id.hex()[:8]} lost "
                        f"a member node; gang re-queued for placement",
                data={"pg_id": record.pg_id.hex(),
                      "strategy": record.strategy})
        if hit:
            self.retry_pending_placement_groups()

    def pending_pg_demand(self) -> List:
        """[(strategy, [bundle resource dicts])] for queued PGs — the
        autoscaler's gang-demand input (reference:
        autoscaler.proto GangResourceRequest)."""
        with self._pg_lock:
            return [(r.strategy, [dict(b.resources) for b in r.bundles])
                    for r in self._pending_pgs]

    def _spec_resources(self, spec: TaskSpec) -> Dict[str, float]:
        from ray_tpu.core.scheduler import _pg_resources
        if (spec.strategy.kind == "PLACEMENT_GROUP"
                and spec.strategy.placement_group_id is not None):
            return _pg_resources(spec.resources,
                                 spec.strategy.placement_group_id,
                                 spec.strategy.bundle_index)
        return spec.resources

    # --- actor routing -------------------------------------------------
    def create_actor(self, spec: TaskSpec, name: Optional[str] = None) -> None:
        record = ActorRecord(
            actor_id=spec.actor_id, name=name or spec.actor_name,
            namespace=self.namespace,
            state="PENDING", spec=spec, max_restarts=spec.max_restarts)
        try:
            self.gcs.register_actor(record)
        except ValueError as e:
            if name is not None:
                raise  # driver call sites expect the synchronous raise
            # Duplicate name arriving via a client/worker SUBMIT (no
            # reply channel): fail the creation task typed — the
            # caller's handle then errors on use instead of the head
            # reader swallowing a traceback.
            self.task_manager.add_pending(spec)
            self._fail_task(spec, e)
            return
        self.actors[spec.actor_id] = ActorInfo(spec)
        self.submit_spec(spec)

    def _fail_task(self, spec: TaskSpec, err: Exception) -> None:
        self.task_manager.fail(spec.task_id, err)
        for oid in spec.return_ids():
            # a failed reconstruction must drop its claims or later
            # try_reconstruct calls would no-op forever
            self._reconstruction_done(oid)
        if spec.num_returns == -1:
            self._finish_stream(spec.task_id, err)

    def _route_actor_task(self, spec: TaskSpec) -> None:
        info = self.actors.get(spec.actor_id)
        record = self.gcs.get_actor(spec.actor_id)
        if info is None or record is None:
            if record is not None and record.state == "ORPHANED":
                # Journal-replayed named actor whose node has not
                # re-registered (yet) after the head restart: fail as
                # unavailable (retryable), not dead.
                self._fail_task(spec, ActorUnavailableError(
                    spec.actor_id,
                    "actor orphaned by a head restart; awaiting its "
                    "node's re-registration"))
                return
            self._fail_task(spec,
                            ActorDiedError(spec.actor_id, "unknown actor"))
            return
        with info.lock:
            if record.state == "DEAD":
                from ray_tpu.devtools import recovery
                self._fail_task(
                    spec,
                    ActorDiedError(
                        spec.actor_id,
                        f"actor is dead: {record.death_cause}"
                        + recovery.incident_tail_text(
                            record.death_event_seq)))
                return
            if not info.ready_for_dispatch or info.worker_id is None:
                info.buffered.append(spec)
                return
            node = self.nodes.get(info.node_id)
        ok = node is not None and node.dispatch_to_actor(info.worker_id, spec)
        if not ok:
            with info.lock:
                info.buffered.append(spec)

    def _flush_actor_buffer(self, actor_id: ActorID) -> None:
        """Drain buffered tasks in order, then open direct dispatch.
        New submissions keep landing in the buffer until the flush
        completes, preserving submission order."""
        info = self.actors.get(actor_id)
        if info is None:
            return
        while True:
            with info.lock:
                if not info.buffered:
                    info.ready_for_dispatch = True
                    return
                spec = info.buffered.popleft()
                node = self.nodes.get(info.node_id)
                worker_id = info.worker_id
            ok = (node is not None and worker_id is not None
                  and node.dispatch_to_actor(worker_id, spec))
            if not ok:
                with info.lock:
                    info.buffered.appendleft(spec)
                return  # actor died mid-flush; death path re-handles

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        info = self.actors.get(actor_id)
        record = self.gcs.get_actor(actor_id)
        if info is None or record is None:
            return
        if no_restart:
            self.gcs.update_actor_state(actor_id, "DEAD",
                                        death_cause="killed via kill()")
        node = self.nodes.get(info.node_id)
        if node is not None and info.worker_id is not None:
            node.kill_worker(info.worker_id)

    # --- completion callbacks (called from node reader threads) ---------
    def on_task_done(self, node: Node, worker, spec: TaskSpec, msg: dict) -> None:
        pending = self.task_manager.get_pending(spec.task_id)
        submitted_at = pending.submitted_at if pending is not None else None
        error_blob = msg.get("error")
        if error_blob is not None:
            err = serialization.loads(error_blob)
            if spec.retry_exceptions and spec.num_returns != -1:
                retry = self.task_manager.consume_retry(spec.task_id)
                if retry is not None:
                    self._release_task_resources(spec, node.node_id)
                    self._resubmit(retry)
                    return
            if spec.is_actor_creation:
                self.gcs.update_actor_state(spec.actor_id, "DEAD",
                                            death_cause=str(err))
                info = self.actors.get(spec.actor_id)
                if info is not None:
                    self._release_actor_resources(info)
                self._fail_actor_buffer(spec.actor_id, err)
            self._record_execution_events(spec, node, worker, msg,
                                          "FAILED",
                                          error=msg.get("error_str"),
                                          submitted_at=submitted_at)
            self._fail_task(spec, err)
            self._release_task_resources(spec, node.node_id)
            if _task_phase._TRACKED:
                _task_phase.finish(spec.task_id, msg.get("t_start"),
                                   msg.get("t_end"))
            self._signal_scheduler()
            return
        for result in msg.get("results", ()):
            oid_bytes, kind, data = result[:3]
            contained = result[3] if len(result) > 3 else ()
            oid = ObjectID(oid_bytes)
            if self._reconstructing:  # unlocked peek: usually empty
                self._reconstruction_done(oid)
            self._pin_contained(oid, contained)
            if kind == "inline":
                from ray_tpu.core.object_transfer import TRANSFER_BYTES
                TRANSFER_BYTES.inc(float(len(data)),
                                   tags={"transport": "inline"})
                self.memory_store.put(oid, ("packed", bytes(data)))
                self.task_manager.set_location_and_ready(
                    oid, ObjectLocation("memory"))
            else:
                self.task_manager.set_location_and_ready(
                    oid, ObjectLocation("shm", node.node_id))
            # fire-and-forget caller may have dropped the result ref
            # already; reclaim after the borrow grace window (checked
            # under the counter lock — races with REF_ADD are safe).
            # Reclaiming the container also unpins its contained refs.
            self.reference_counter.delete_if_unreferenced(
                oid, defer=(self._ref_grace_s, self._schedule_expiry))
        if spec.is_actor_creation:
            info = self.actors.get(spec.actor_id)
            record = self.gcs.get_actor(spec.actor_id)
            if record is not None and record.state == "DEAD":
                # kill() raced the construction: honor the kill instead of
                # reviving (reference: GCS actor manager kill-on-pending).
                node.kill_worker(worker.worker_id)
                if info is not None:
                    self._release_actor_resources(info)
                    self._fail_actor_buffer(
                        spec.actor_id,
                        ActorDiedError(spec.actor_id, "actor killed"))
            elif info is not None:
                with info.lock:
                    info.node_id = node.node_id
                    info.worker_id = worker.worker_id
                self.gcs.update_actor_state(spec.actor_id, "ALIVE",
                                            node_id=node.node_id)
                self._flush_actor_buffer(spec.actor_id)
            self.task_manager.complete(spec.task_id)
            # Creation resources stay held for the actor's lifetime.
        else:
            self.task_manager.complete(spec.task_id)
            if spec.num_returns == -1:
                self._finish_stream(spec.task_id, None)
            self._record_lineage(spec)
            self._release_task_resources(spec, node.node_id)
        self._record_execution_events(spec, node, worker, msg, "FINISHED",
                                      submitted_at=submitted_at)
        if _task_phase._TRACKED:
            _task_phase.finish(spec.task_id, msg.get("t_start"),
                               msg.get("t_end"))
        self._signal_scheduler()

    def _consume_overcommit(self, task_id: TaskID) -> bool:
        """True if this spec was burst-granted (holds NO scheduler
        resources); consumes the marker so each release path sees it
        exactly once. set.remove is atomic under the GIL."""
        try:
            # GIL-atomic (per docstring); a lock here would nest inside
            # every release path's existing locks for no added safety
            self._overcommitted.remove(task_id)  # graftlint: disable=GL001
            return True
        except KeyError:
            return False

    def _release_task_resources(self, spec: TaskSpec, node_id: NodeID) -> None:
        if spec.actor_id is not None:
            # Method tasks hold no scheduler resources; creation resources
            # are owned by the actor lifecycle (_release_actor_resources).
            return
        if self._consume_overcommit(spec.task_id):
            return
        self.scheduler.release(node_id, self._spec_resources(spec),
                               token=spec.task_id)

    def _signal_scheduler(self) -> None:
        # cheap unlocked read: only completions that may unblock a
        # capacity-starved backlog pay the lock+notify+context switch
        if not getattr(self, "_backlog_blocked", True):
            return
        with self._sched_cond:
            self._sched_cond.notify_all()

    def _resubmit(self, spec: TaskSpec) -> None:
        if spec.actor_id is not None and not spec.is_actor_creation:
            self._route_actor_task(spec)
        else:
            deps = [d for d in spec.dependencies()
                    if not self.task_manager.is_ready(d)]
            if deps:
                remaining = [len(deps)]
                lock = threading.Lock()

                def on_dep_ready():
                    with lock:
                        remaining[0] -= 1
                        if remaining[0]:
                            return
                    self._enqueue(spec)

                for dep in deps:
                    self.task_manager.on_ready(dep, on_dep_ready)
            else:
                self._enqueue(spec)

    def on_worker_crashed(self, node: Node, worker, running: List[TaskSpec],
                          actor_id: Optional[ActorID]) -> None:
        cfg = get_config()
        self._drop_worker_subscriptions(node.node_id,
                                        worker.worker_id.binary())
        # node.py's death observer emits WORKER_EXIT and stashes the seq
        # on the handle; paths that bypass it (remove_node kills after
        # stop()) emit here so the incident always has a root event.
        exit_seq = getattr(worker, "_exit_event_seq", None)
        if exit_seq is None:
            cause = getattr(worker, "_exit_cause_seq", None)
            if cause is None:
                # Remote/virtual worker kills: the stub is minted per
                # message, so chaos stashes its CHAOS_INJECTED seq on
                # the head-side node keyed by worker id (one-shot).
                causes = getattr(node, "_chaos_worker_causes", None)
                if causes:
                    cause = causes.pop(worker.worker_id, None)
            exit_seq = self.gcs.add_cluster_event(
                "WORKER_EXIT", "ERROR", node_id=node.node_id,
                worker_id=worker.worker_id,
                caused_by=cause,
                message="worker killed with its node")
        if exit_seq is not None and (running or actor_id is not None):
            # idle reclaims carry a seq too but seed no recovery chain
            self._last_death_seq = exit_seq
        for spec in running:
            if (not spec.is_actor_creation and spec.actor_id is None
                    and not self._consume_overcommit(spec.task_id)):
                self.scheduler.release(node.node_id,
                                       self._spec_resources(spec),
                                       token=spec.task_id)
            # Streaming tasks never retry: already-consumed yields would
            # replay (reference keeps generator retries behind a flag for
            # the same reason).
            retry = (None if spec.num_returns == -1
                     else self.task_manager.consume_retry(spec.task_id))
            if retry is not None and not spec.is_actor_creation:
                self._emit_task_retry(retry, exit_seq)
                self._resubmit(retry)
            elif spec.is_actor_creation:
                pass  # handled by actor restart below
            else:
                msg = (f"worker {worker.worker_id.hex()[:8]} died while "
                       f"running {spec.name or spec.function_id}")
                # post-mortem: the collector still holds the dead
                # process's last-flushed journal
                from ray_tpu.util import flight_recorder
                msg += flight_recorder.store_tail_text(
                    f"worker:{worker.worker_id.hex()[:12]}")
                err: Exception = WorkerCrashedError(msg)
                if spec.actor_id is not None:
                    err = ActorUnavailableError(spec.actor_id, str(err))
                self._record_event(spec, "FAILED", node_id=node.node_id,
                                  error=str(err))
                self._fail_task(spec, err)
        if actor_id is not None or any(s.is_actor_creation for s in running):
            aid = actor_id or next(
                s.actor_id for s in running if s.is_actor_creation)
            self._handle_actor_death(aid, node, cause_seq=exit_seq)
        self._signal_scheduler()

    def _release_actor_resources(self, info: ActorInfo,
                                 dead_node=None) -> None:
        """Release the creation-task resources exactly once per incarnation
        (covers kill(), crash during __init__, and death while ALIVE).
        ``dead_node``: when releasing because that node died, the ledger
        died with it (scheduler.remove_node) — and if the same node id
        re-registered in the meantime, a by-id release would credit the
        NEW incarnation's fresh ledger with capacity it never granted
        (oversubscribing it), so release only onto the live object."""
        node_id = info.resources_node
        if node_id is None:
            return
        info.resources_node = None
        if (dead_node is not None
                and self.nodes.get(node_id) is not dead_node):
            return
        self.scheduler.release(node_id,
                               self._spec_resources(info.creation_spec),
                               token=info.creation_spec.task_id)

    def _handle_actor_death(self, actor_id: ActorID, node: Node,
                            cause_seq: Optional[int] = None) -> None:
        record = self.gcs.get_actor(actor_id)
        info = self.actors.get(actor_id)
        if record is None or info is None:
            return
        with info.lock:  # captured before the restart path clears it
            dead_worker = info.worker_id
        dead_node = node if getattr(node, "is_remote", False) else None
        self._release_actor_resources(info, dead_node=dead_node)
        if record.state == "DEAD":
            self._fail_actor_buffer(actor_id,
                                    ActorDiedError(actor_id, "actor killed"))
            return
        can_restart = (record.max_restarts == -1
                       or record.num_restarts < record.max_restarts)
        if info.creation_spec is None:
            # Re-adopted after a head restart with an unjournalable
            # creation spec: re-attach worked, restart cannot.
            can_restart = False
        if can_restart:
            record.num_restarts += 1
            with info.lock:
                info.node_id = None
                info.worker_id = None
                info.ready_for_dispatch = False
            new_spec = TaskSpec(
                task_id=TaskID.from_random(),
                function_id=info.creation_spec.function_id,
                args=info.creation_spec.args,
                kwargs=info.creation_spec.kwargs,
                num_returns=1,
                resources=info.creation_spec.resources,
                strategy=info.creation_spec.strategy,
                max_retries=0,
                name=info.creation_spec.name,
                actor_id=actor_id,
                is_actor_creation=True,
                max_restarts=info.creation_spec.max_restarts,
                max_concurrency=info.creation_spec.max_concurrency,
                # keep the restarted actor on the original creation
                # trace (GL007): restarts are hops in the same request
                trace_id=info.creation_spec.trace_id,
                parent_span_id=info.creation_spec.parent_span_id,
            )
            info.creation_spec = new_spec
            self.gcs.update_actor_state(actor_id, "RESTARTING",
                                        cause_seq=cause_seq)
            self.task_manager.add_pending(new_spec)
            self._enqueue(new_spec)
        else:
            death_seq = self.gcs.update_actor_state(
                actor_id, "DEAD", death_cause="worker died",
                cause_seq=cause_seq)
            msg = "actor worker died"
            if dead_worker is not None:
                # post-mortem: the collector still holds the dead
                # process's last-flushed journal — name what it was
                # doing in its final moments
                from ray_tpu.util import flight_recorder
                msg += flight_recorder.store_tail_text(
                    f"worker:{dead_worker.hex()[:12]}")
            # ... and the incident timeline the death belongs to, in
            # the same attach-the-tail mold
            from ray_tpu.devtools import recovery
            msg += recovery.incident_tail_text(death_seq)
            self._fail_actor_buffer(
                actor_id, ActorDiedError(actor_id, msg))

    def _fail_actor_buffer(self, actor_id: ActorID, err: Exception) -> None:
        info = self.actors.get(actor_id)
        if info is None:
            return
        with info.lock:
            buffered = list(info.buffered)
            info.buffered.clear()
        for spec in buffered:
            self._fail_task(spec, err)

    # --- object plane ---------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        with serialization.collect_contained_refs() as contained:
            data, buffers = serialization.serialize(value)
        ref = self.put_serialized(data, buffers)
        self._pin_contained(ref.id, contained)
        return ref

    def put_serialized(self, data: bytes, buffers) -> ObjectRef:
        """Store already-serialized parts (single serialize pass)."""
        oid = ObjectID.from_random()
        cfg = get_config()
        if not buffers and len(data) < cfg.max_inline_object_size:
            packed = serialization.pack_parts(data, buffers)
            self.memory_store.put(oid, ("packed", packed))
            location = ObjectLocation("memory")
        else:
            head = self.nodes[self.head_node_id]
            sizes = [b.nbytes for b in buffers]
            from ray_tpu.exceptions import ObjectStoreFullError
            try:
                head.store.put_parts(oid, data, buffers, sizes)
            except ObjectStoreFullError:
                # spill referenced objects to disk, then retry
                self.spill_on_node(
                    head, serialization.packed_size(data, sizes))
                head.store.put_parts(oid, data, buffers, sizes)
            location = ObjectLocation("shm", self.head_node_id)
        self.task_manager.set_location_and_ready(oid, location)
        return ObjectRef(oid)

    def store_packed_object(self, oid: ObjectID, packed: bytes,
                            contained=()) -> None:
        """Store an already-packed payload under a given id (client-mode
        puts: the client ships packed bytes, the head owns the object).
        Small payloads go to the memory store; large ones into the head
        arena via a raw create/seal write."""
        cfg = get_config()
        if len(packed) < cfg.max_inline_object_size:
            self.memory_store.put(oid, ("packed", packed))
            location = ObjectLocation("memory")
        else:
            head = self.nodes[self.head_node_id]
            from ray_tpu.exceptions import ObjectStoreFullError
            try:
                buf = head.store.create(oid, len(packed))
            except ObjectStoreFullError:
                self.spill_on_node(head, len(packed))
                buf = head.store.create(oid, len(packed))
            try:
                buf[:] = packed
            finally:
                del buf
            head.store.seal(oid)
            location = ObjectLocation("shm", self.head_node_id)
        if contained:
            self._pin_contained(oid, contained)
        self.task_manager.set_location_and_ready(oid, location)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            out.append(self._get_one(ref.id, remaining))
        return out[0] if single else out

    def _get_one(self, oid: ObjectID, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        for attempt in range(3):
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not self.task_manager.wait_ready(oid, remaining):
                raise GetTimeoutError(f"get() timed out waiting for {oid}")
            err = self.task_manager.get_error(oid)
            if err is not None:
                if (attempt < 2
                        and self._reconstruct_after_infra_failure(oid, err)):
                    continue
                raise err
            found, stored = self.memory_store.get(oid, timeout_s=0)
            if found:
                kind, payload = stored
                return (serialization.unpack(payload)
                        if kind == "packed" else payload)
            loc = self.task_manager.get_location(oid)
            if loc is not None and loc.kind == "spilled":
                value = self._read_spilled(oid, loc)
                if value is not _SPILL_MISS:
                    return value
            holders = self.object_holders(oid)
            # Prefer a copy in an in-process store (zero-copy read).
            for nid in holders:
                node = self.nodes.get(nid)
                if node is None or getattr(node, "is_remote", False):
                    continue
                found, value = node.store.get_value(oid, timeout_s=5.0)
                if found:
                    return value
            # Remote holders only: pull chunked into the head store
            # (reference: PullManager-driven transfer, pull_manager.h:50).
            head = self.nodes.get(self.head_node_id)
            if head is not None:
                from ray_tpu.core.object_transfer import get_pull_manager
                for nid in holders:
                    node = self.nodes.get(nid)
                    if node is None or not getattr(node, "is_remote", False):
                        continue
                    if get_pull_manager().pull(node.object_addr, oid,
                                               head.store):
                        self.add_object_replica(oid, self.head_node_id)
                        found, value = head.store.get_value(oid,
                                                            timeout_s=5.0)
                        if found:
                            return value
            # Every copy is gone: lineage reconstruction re-executes the
            # producer, then we wait for readiness again.
            if not self.try_reconstruct(oid):
                break
        raise ObjectLostError(oid)

    def _read_spilled(self, oid: ObjectID, loc: ObjectLocation):
        """Read a spilled payload. Local file: unpack directly. File on
        a remote host: pull it chunked off the daemon's object server
        (which serves spill files) into the head arena."""
        import os as _os
        if loc.path and _os.path.exists(loc.path):
            with open(loc.path, "rb") as f:
                return serialization.unpack(f.read())
        node = self.nodes.get(loc.node_id)
        head = self.nodes.get(self.head_node_id)
        if (node is not None and getattr(node, "is_remote", False)
                and head is not None):
            from ray_tpu.core.object_transfer import get_pull_manager
            if get_pull_manager().pull(node.object_addr, oid, head.store):
                self.add_object_replica(oid, self.head_node_id)
                found, value = head.store.get_value(oid, timeout_s=5.0)
                if found:
                    return value
        return _SPILL_MISS

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if num_returns > len(refs):
            raise ValueError(
                f"num_returns ({num_returns}) exceeds the number of refs "
                f"({len(refs)})")
        event = threading.Event()
        for ref in refs:
            self.task_manager.on_ready(ref.id, event.set)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready = [r for r in refs if self.task_manager.is_ready(r.id)]
            if len(ready) >= num_returns:
                break
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                break
            event.clear()
            event.wait(remaining if remaining is not None else 0.2)
        done = ready[:num_returns]
        done_set = {r.id for r in done}
        rest = [r for r in refs if r.id not in done_set]
        return done, rest

    def _pin_contained(self, container: ObjectID, contained) -> None:
        """Objects referenced inside a stored value stay alive as long as
        the container does (reference: reference_counter.h nested-ref
        tracking). `contained` is a list of ObjectID binaries."""
        if not contained:
            return
        oids = [b if isinstance(b, ObjectID) else ObjectID(b)
                for b in contained]
        led = refsan.LEDGER
        for oid in oids:
            if led is not None:
                led.record(refsan.KIND_PIN_CONTAINED, oid.hex(),
                           {"container": container.hex()})
            self.reference_counter.add_local_reference(oid)
        with self._contained_lock:
            self._contained_refs.setdefault(container, []).extend(oids)

    def _maybe_delete_object(self, oid: ObjectID) -> None:
        """Called when the local reference count drops to zero
        (reference: reference_counter.h — delete at refcount 0)."""
        stopped = getattr(self, "_stopped", None)
        if stopped is not None and stopped.is_set():
            return  # shutdown: shm arenas may already be unmapped
        if not self.task_manager.is_ready(oid):
            return  # producing task still running; keep bookkeeping
        led = refsan.LEDGER
        if led is not None:
            # Point of no return for this oid: any owner-side borrow
            # registration sequenced after this event is a grace
            # violation (the PR-13 class).
            led.record(refsan.KIND_DELETED, oid.hex())
        self.memory_store.delete(oid)
        loc = self.task_manager.get_location(oid)
        targets = set()
        if loc is not None and loc.node_id is not None:
            targets.add(loc.node_id)
        with self._replica_lock:
            targets.update(self._object_replicas.pop(oid, ()))
        for nid in targets:
            node = self.nodes.get(nid)
            if node is not None:
                node.store.delete(oid)
        if loc is not None and loc.kind == "spilled" and loc.path:
            try:
                os.unlink(loc.path)
            except OSError:
                pass  # remote file: the daemon's DELETE_OBJECT removes it
        self.task_manager.forget_object(oid)
        with self._contained_lock:
            nested = self._contained_refs.pop(oid, None)
        if nested:
            for inner in nested:  # may recurse through nested containers
                self.reference_counter.remove_local_reference(inner)

    def _expiry_loop(self) -> None:
        import heapq
        # bootstrap spin: _stopped is created later in __init__, so
        # there is no Event to wait on yet
        while getattr(self, "_stopped", None) is None:  # graftlint: disable=GL003
            time.sleep(0.05)  # started early in __init__
        while not self._stopped.is_set():
            with self._expiry_cv:
                while not self._expiry_items:
                    self._expiry_cv.wait(0.5)
                    if self._stopped.is_set():
                        return
                deadline, _, fn = self._expiry_items[0]
                now = time.monotonic()
                if deadline > now:
                    self._expiry_cv.wait(min(deadline - now, 0.5))
                    continue
                heapq.heappop(self._expiry_items)
            try:
                fn()
            except Exception:
                logger.exception("expiry callback failed")

    def _state_dump_loop(self) -> None:
        import json
        import tempfile
        pointer = os.path.join(tempfile.gettempdir(),
                               "ray_tpu_last_session.json")
        # bootstrap spin: this thread starts early in __init__,
        # before _stopped exists
        while getattr(self, "_stopped", None) is None:  # graftlint: disable=GL003
            time.sleep(0.05)
        while not self._stopped.wait(2.0):
            try:
                from ray_tpu.util import state as state_mod
                head = self.nodes.get(self.head_node_id)
                if head is None:
                    continue
                path = os.path.join(head.session_dir, "state.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state_mod.state_snapshot(), f)
                os.replace(tmp, path)
                pointer_tmp = f"{pointer}.{os.getpid()}.tmp"
                with open(pointer_tmp, "w") as f:
                    json.dump({"state_path": path,
                               "session_dir": head.session_dir,
                               "pid": os.getpid()}, f)
                os.replace(pointer_tmp, pointer)
            except Exception:  # graftlint: disable=GL004
                pass  # state dump is best-effort observability

    def _schedule_expiry(self, delay: float, fn) -> None:
        import heapq
        with self._expiry_cv:
            heapq.heappush(
                self._expiry_items,
                (time.monotonic() + delay, id(fn), fn))
            # No notify: the expiry thread polls at >=2 Hz and every
            # deadline is >=grace seconds out, so a wakeup per scheduled
            # item would only thrash the GIL on the task hot path.

    def deferred_remove_reference(self, oid: ObjectID) -> None:
        """Remove a worker-reported borrow; a zero count only fires the
        deleter after a grace window (and only if still zero), masking
        the gap between a worker dropping a returned ref and the caller
        registering its borrow. Containment pinning (task returns / puts
        that embed refs) covers the durable cases; the grace window only
        guards transient hand-offs."""
        self.reference_counter.remove_local_reference(
            oid, defer=(self._ref_grace_s, self._schedule_expiry))

    # --- object spilling --------------------------------------------------
    # reference: raylet LocalObjectManager spilling under memory pressure
    # (local_object_manager.h:43) + external_storage.py file layout.
    @staticmethod
    def _spill_dir_for(node) -> str:
        base = node.session_dir
        if not base:
            import tempfile
            base = tempfile.gettempdir()
        path = os.path.join(base, "spill")
        os.makedirs(path, exist_ok=True)
        return path

    def handle_spill_request(self, node, worker, msg: dict) -> None:
        """A worker's create() hit a full arena: free space by spilling
        referenced sealed objects to disk, then let it retry."""
        needed = int(msg.get("bytes", 0)) or 1
        if getattr(node, "is_remote", False):
            candidates = [
                oid.binary()
                for oid in self.task_manager.objects_on_node(node.node_id)
                if (loc := self.task_manager.get_location(oid)) is not None
                and loc.kind == "shm" and self.task_manager.is_ready(oid)
            ]
            node.send({"kind": "SPILL_OBJECTS", "object_ids": candidates,
                       "bytes": needed,
                       "reply_worker": worker.worker_id.binary(),
                       "req_id": msg.get("req_id")})
            return
        freed = self.spill_on_node(node, needed)
        worker.send({"kind": "SPILL_REPLY", "req_id": msg.get("req_id"),
                     "freed": freed})

    def spill_on_node(self, node, needed: int) -> int:
        """Spill ready shm objects from an in-process node's arena to
        disk until `needed` bytes are freed. Returns bytes freed."""
        if not get_config().object_spill_enabled:
            return 0
        from ray_tpu.core.object_store import spill_objects
        candidates = [
            oid for oid in self.task_manager.objects_on_node(node.node_id)
            if (loc := self.task_manager.get_location(oid)) is not None
            and loc.kind == "shm" and self.task_manager.is_ready(oid)
        ]
        results = spill_objects(node.store, self._spill_dir_for(node),
                                candidates, needed)
        for oid, path, _size in results:
            self.task_manager.set_location(
                oid, ObjectLocation("spilled", node.node_id, path))
        freed = sum(size for _, _, size in results)
        if results:
            self.gcs.add_cluster_event(
                "OBJECT_SPILLED", "WARNING", node_id=node.node_id,
                message=f"{len(results)} objects spilled under arena "
                        "pressure",
                data={"bytes": freed, "count": len(results)})
        return freed

    def on_objects_spilled(self, node, msg: dict) -> None:
        """A daemon spilled objects on our request: record locations and
        unblock the waiting worker."""
        results = msg.get("results", ())
        for oid_bytes, path, _size in results:
            self.task_manager.set_location(
                ObjectID(oid_bytes),
                ObjectLocation("spilled", node.node_id, path))
        if results:
            self.gcs.add_cluster_event(
                "OBJECT_SPILLED", "WARNING", node_id=node.node_id,
                message=f"{len(results)} objects spilled under arena "
                        "pressure",
                data={"bytes": sum(r[2] for r in results),
                      "count": len(results)})
        reply_worker = msg.get("reply_worker")
        if reply_worker is not None:
            from ray_tpu.core.remote_node import RemoteWorkerStub
            RemoteWorkerStub(node, WorkerID(reply_worker)).send(
                {"kind": "SPILL_REPLY", "req_id": msg.get("req_id"),
                 "freed": msg.get("freed", 0)})

    # --- worker message handlers ----------------------------------------
    def on_worker_put(self, node: Node, msg: dict) -> None:
        oid = ObjectID(msg["object_id"])
        self._pin_contained(oid, msg.get("contained", ()))
        self.task_manager.set_location_and_ready(
            oid, ObjectLocation("shm", node.node_id))

    def handle_get_object(self, node: Node, worker, msg: dict) -> None:
        oid = ObjectID(msg["object_id"])
        req_id = msg.get("req_id")
        attempts = [0]

        def reply():
            out = {"kind": "OBJECT_VALUE", "req_id": req_id}
            err = self.task_manager.get_error(oid)
            if err is not None:
                if (attempts[0] < 2
                        and self._reconstruct_after_infra_failure(oid, err)):
                    attempts[0] += 1
                    self.task_manager.on_ready(oid, reply)
                    return
                out.update(status="error", error=serialization.dumps(err))
                worker.send(out)
                return
            found, stored = self.memory_store.get(oid, timeout_s=0)
            if found:
                kind, payload = stored
                out.update(status="inline", data=payload)
                worker.send(out)
                return
            loc = self.task_manager.get_location(oid)
            if loc is not None and loc.kind == "spilled":
                holder = self.nodes.get(loc.node_id)
                holder_remote = getattr(holder, "is_remote", False)
                requester_remote = getattr(node, "is_remote", False)
                # File readable on the requester's host: its own spill,
                # or (for in-process requesters, which share the head's
                # host) any file spilled by an in-process node.
                if loc.path and (loc.node_id == node.node_id
                                 or (not requester_remote
                                     and not holder_remote)):
                    out.update(status="spilled_local", path=loc.path)
                    worker.send(out)
                    return
                if requester_remote:
                    # the holder's object server streams spill files
                    addr = (holder.object_addr if holder_remote
                            else (self.object_server.address
                                  if self.object_server else None))
                    if addr is not None:
                        out.update(status="pull", addr=list(addr),
                                   object_id=oid.binary())
                    else:
                        out.update(status="error",
                                   error=serialization.dumps(
                                       ObjectLostError(oid)))
                    worker.send(out)
                    return
                # in-process requester, file on a remote host: pull it
                # into the requester's arena off the reader thread
                threading.Thread(
                    target=self._replicate_and_reply,
                    args=(oid, node, worker, out), daemon=True).start()
                return
            if loc is not None and loc.kind == "shm":
                holders = self.object_holders(oid)
                if not holders:
                    # every copy died with its node: reconstruct via
                    # lineage, then re-arm this reply on readiness
                    if attempts[0] < 2 and self.try_reconstruct(oid):
                        attempts[0] += 1
                        self.task_manager.on_ready(oid, reply)
                        return
                    out.update(status="error", error=serialization.dumps(
                        ObjectLostError(oid)))
                    worker.send(out)
                    return
                if node.node_id in holders:
                    out.update(status="shm_local")
                    worker.send(out)
                    return
                if getattr(node, "is_remote", False):
                    # Point the daemon at a holder; it pulls chunked
                    # node-to-node (reference: object_manager.proto:63
                    # chunked Push/Pull).
                    addr = self._holder_object_addr(holders)
                    if addr is None:
                        out.update(status="error",
                                   error=serialization.dumps(
                                       ObjectLostError(oid)))
                    else:
                        out.update(status="pull", addr=list(addr),
                                   object_id=oid.binary())
                    worker.send(out)
                    return
                # In-process requester: replicate into its store off the
                # callback thread, then report it local.
                threading.Thread(
                    target=self._replicate_and_reply,
                    args=(oid, node, worker, out), daemon=True).start()
                return
            out.update(status="error",
                       error=serialization.dumps(ObjectLostError(oid)))
            worker.send(out)

        self.task_manager.on_ready(oid, reply)

    def _holder_object_addr(self, holders: List[NodeID]):
        """Object-server address of some node holding the object."""
        for nid in holders:
            node = self.nodes.get(nid)
            if node is None:
                continue
            if getattr(node, "is_remote", False):
                return node.object_addr
            if self.object_server is not None:
                return self.object_server.address
        return None

    def _replicate_and_reply(self, oid: ObjectID, dst_node: Node,
                             worker, out: dict) -> None:
        if self._replicate_to_node(oid, dst_node):
            self.add_object_replica(oid, dst_node.node_id)
            out.update(status="shm_local")
        else:
            out.update(status="error",
                       error=serialization.dumps(ObjectLostError(oid)))
        worker.send(out)

    def _replicate_to_node(self, oid: ObjectID, dst_node: Node) -> bool:
        """Copy a sealed object into ``dst_node``'s store from any holder
        (in-process: direct memcpy between arenas; remote: chunked pull)."""
        if dst_node.store.contains(oid):
            return True
        loc = self.task_manager.get_location(oid)
        if loc is not None and loc.kind == "spilled":
            src = self.nodes.get(loc.node_id)
            if src is not None and getattr(src, "is_remote", False):
                from ray_tpu.core.object_transfer import (
                    PRIORITY_TASK_ARG, get_pull_manager)
                return get_pull_manager().pull(src.object_addr, oid,
                                               dst_node.store,
                                               priority=PRIORITY_TASK_ARG)
            return False  # local files are served via spilled_local
        for nid in self.object_holders(oid):
            src = self.nodes.get(nid)
            if src is None or nid == dst_node.node_id:
                continue
            if getattr(src, "is_remote", False):
                from ray_tpu.core.object_transfer import (
                    PRIORITY_TASK_ARG, get_pull_manager)
                if get_pull_manager().pull(src.object_addr, oid,
                                           dst_node.store,
                                           priority=PRIORITY_TASK_ARG):
                    return True
                continue
            buf = src.store.get_buffer(oid, timeout_s=2.0)
            if buf is None:
                continue
            try:
                try:
                    dest = dst_node.store.create(oid, len(buf))
                except FileExistsError:
                    probe = dst_node.store.get_buffer(oid, timeout_s=10.0)
                    if probe is None:
                        continue
                    del probe
                    dst_node.store.release(oid)
                    return True
                try:
                    dest[:] = buf
                finally:
                    del dest
                dst_node.store.seal(oid)
                from ray_tpu.core.object_transfer import TRANSFER_BYTES
                TRANSFER_BYTES.inc(float(len(buf)),
                                   tags={"transport": "shm_copy"})
                return True
            finally:
                del buf
                src.store.release(oid)
        return False

    def handle_check_ready(self, worker, msg: dict) -> None:
        ready = [b for b in msg["object_ids"]
                 if self.task_manager.is_ready(ObjectID(b))]
        worker.send({"kind": "READY_REPLY", "req_id": msg.get("req_id"),
                     "ready": ready})

    def subscribe_channel(self, channel: str, callback) -> None:
        """Driver-side pubsub subscription (workers reach the same
        publisher through SUBSCRIBE messages; reference: publisher.h:245
        long-poll push — here a direct push over the worker socket)."""
        self.gcs.pubsub.subscribe(channel, callback)

    def publish_channel(self, channel: str, message: Any) -> None:
        self.gcs.pubsub.publish(channel, message)

    def handle_subscribe(self, node, worker, msg: dict) -> None:
        """A worker subscribed to a pubsub channel: push every publish
        to its socket. Routes are tracked per worker so death cleanup
        removes them (a remote worker's stub send can't observe its
        death — the daemon connection stays alive)."""
        channel = msg["channel"]

        def push(payload):
            ok = worker.send({"kind": "PUBSUB_MSG", "channel": channel,
                              "data": serialization.dumps(payload)})
            if not ok:
                self.gcs.pubsub.unsubscribe(channel, push)

        key = (node.node_id, worker.worker_id.binary())
        with self._worker_subs_lock:
            self._worker_subs.setdefault(key, []).append((channel, push))
        self.gcs.pubsub.subscribe(channel, push)

    def _drop_worker_subscriptions(self, node_id: NodeID,
                                   worker_id_bytes: Optional[bytes] = None
                                   ) -> None:
        """Unsubscribe a dead worker's (or a dead node's every worker's)
        pubsub push routes."""
        with self._worker_subs_lock:
            if worker_id_bytes is not None:
                doomed = {(node_id, worker_id_bytes):
                          self._worker_subs.pop(
                              (node_id, worker_id_bytes), [])}
            else:
                doomed = {k: self._worker_subs.pop(k)
                          for k in [k for k in self._worker_subs
                                    if k[0] == node_id]}
        for subs in doomed.values():
            for channel, push in subs:
                self.gcs.pubsub.unsubscribe(channel, push)

    def handle_gcs_request(self, worker, msg: dict) -> None:
        method = msg["method"]
        args = serialization.loads(msg["args"])
        out = {"kind": "GCS_REPLY", "req_id": msg.get("req_id"), "error": None}
        if method == "kv_wait":
            # Async on the head side: this runs on a node's single IO
            # thread, which must never block — the reply is sent by the
            # KV waiter callback when the key lands (or by the timer).
            key, namespace, timeout = args
            import threading as _threading
            claim_lock = _threading.Lock()
            claimed = [False]
            timer_box: list = []

            def _reply(value) -> None:
                # atomic claim: the put callback and the timeout timer
                # race — exactly one may send the reply (a lost put
                # must not be overwritten by the timer's None)
                with claim_lock:
                    if claimed[0]:
                        return
                    claimed[0] = True
                if timer_box:
                    timer_box[0].cancel()
                out["result"] = serialization.dumps(value)
                worker.send(out)

            existing = self.gcs.kv.add_waiter(key, namespace, _reply)
            if existing is not None:
                _reply(existing)
                return

            def _expire() -> None:
                self.gcs.kv.remove_waiter(key, namespace, _reply)
                _reply(None)

            timer = _threading.Timer(timeout, _expire)
            timer.daemon = True
            timer_box.append(timer)
            timer.start()
            return
        try:
            result = self._gcs_dispatch(method, args)
            out["result"] = serialization.dumps(result)
        except Exception as e:  # noqa: BLE001
            out["error"] = serialization.dumps(e)
            out["result"] = None
        worker.send(out)

    def _gcs_dispatch(self, method: str, args: tuple) -> Any:
        gcs = self.gcs
        if method == "get_function":
            return gcs.get_function(args[0])
        if method == "put_function":
            gcs.put_function(args[0], args[1])
            return True
        if method == "node_labels":
            rec = gcs.nodes.get(NodeID(args[0]))
            return dict(rec.labels) if rec else {}
        if method == "kv_put":
            if args[2] == "actor_handles":
                # A named-actor handle may only be installed by the
                # registration that actually OWNS the name: a client
                # whose duplicate-name create_actor failed would
                # otherwise overwrite the live actor's handle with one
                # pointing at a never-registered actor id (the client
                # sends kv_put after SUBMIT on the same ordered
                # connection, so the record exists here by now).
                handle = serialization.loads(args[1])
                name = args[0].decode()
                rec = gcs.get_named_actor(name, self.namespace)
                if rec is None or rec.actor_id != handle._actor_id:
                    return False
            gcs.kv.put(args[0], args[1], namespace=args[2])
            return True
        if method == "kv_get":
            return gcs.kv.get(args[0], namespace=args[1])
        if method == "kv_del":
            return gcs.kv.delete(args[0], namespace=args[1])
        if method == "kv_keys":
            return gcs.kv.keys(args[0], namespace=args[1])
        if method == "kv_exists":
            return gcs.kv.exists(args[0], namespace=args[1])
        if method == "kv_wait":
            # driver-direct path (worker requests take the async branch
            # in handle_gcs_request): blocking is fine on a user thread
            return gcs.kv.wait(args[0], namespace=args[1], timeout=args[2])
        if method == "actor_state":
            rec = gcs.get_actor(ActorID(args[0]))
            return rec.state if rec else None
        if method == "get_named_actor_handle":
            return gcs.kv.get(args[0].encode(), namespace="actor_handles")
        if method == "cluster_resources":
            return self.cluster_resources()
        if method == "available_resources":
            return self.available_resources()
        if method == "list_nodes":
            return [{
                "NodeID": rec.node_id.hex(),
                "Alive": rec.alive,
                "Resources": dict(rec.resources_total),
                "Labels": dict(rec.labels),
            } for rec in gcs.alive_nodes()]
        if method == "publish":
            self.gcs.pubsub.publish(args[0], serialization.loads(args[1]))
            return True
        if method == "metrics_apply":
            from ray_tpu.util.metrics import _registry
            kind, name, tag_items, value, boundaries = args
            _registry.apply(kind, name, tuple(tag_items), value,
                            boundaries)
            return True
        if method == "metrics_apply_batch":
            from ray_tpu.util.metrics import _registry
            _registry.apply_batch(args[0])
            return True
        if method == "trace_add_span":
            self.gcs.add_trace_span(args[0])
            return True
        if method == "flight_sync":
            # clock ping-pong: the worker brackets this call with its
            # own clock reads and derives its offset into our domain
            from ray_tpu.util import flight_recorder
            return flight_recorder.clock_ns()
        if method == "flight_push":
            # journal increment from a worker flusher; brief/lock-only
            # (this may run on the head's IO-loop thread)
            from ray_tpu.util import flight_recorder
            flight_recorder.store_push(args[0], args[1], args[2])
            return True
        if method == "refsan_push":
            # lifetime-ledger increment from a worker's refsan flusher;
            # same brevity contract as flight_push
            refsan.store_push(args[0], args[1])
            return True
        if method == "collsan_push":
            # collective-fingerprint increment from a worker's collsan
            # flusher; same brevity contract as flight_push
            from ray_tpu.devtools import collsan
            collsan.store_push(args[0], args[1])
            return True
        if method == "profile_push":
            # cumulative profile snapshot from a worker's sampler;
            # replace-on-push, same brevity contract as flight_push
            from ray_tpu.devtools import profiler
            profiler.store_push(args[0], args[1], args[2], args[3])
            return True
        if method == "add_cluster_event":
            # lifecycle event from a worker process (serve controller /
            # replicas route here via events.emit); brief/lock-only
            (kind, severity, node_id, worker_id, actor_id, task_id,
             message, caused_by, data) = args
            return gcs.add_cluster_event(
                kind, severity, node_id=node_id, worker_id=worker_id,
                actor_id=actor_id, task_id=task_id, message=message,
                caused_by=caused_by, data=data)
        if method == "list_cluster_events":
            return [e.to_dict() for e in gcs.list_cluster_events(*args)]
        raise ValueError(f"unknown GCS method {method}")

    # --- misc api --------------------------------------------------------
    def gcs_call(self, method: str, *args) -> Any:
        return self._gcs_dispatch(method, args)

    def cancel(self, object_id: ObjectID, force: bool = False) -> None:
        """Cancel the producing task: tasks not yet dispatched (queued,
        dep-waiting, or in the scheduler's backlog) fail with
        TaskCancelledError immediately — the scheduling loop drops specs
        whose pending entry is gone. Running tasks are only interrupted
        with force=True (worker kill), matching the reference's
        semantics for non-async tasks."""
        task_id = self.task_manager.producing_task(object_id)
        if task_id is None:
            return
        task = self.task_manager.get_pending(task_id)
        if task is None:
            return  # already finished/failed
        if task.node_id is None and task.spec.actor_id is None:
            # Plain task not dispatched anywhere yet; fail it and let the
            # queues drop it when they encounter the dead pending entry.
            # Actor tasks are excluded: they are routed to the actor
            # without mark_dispatched, so node_id is None even while the
            # method runs — cancelling them here would fail the ref while
            # the method still executes (only force=True interrupts).
            self.task_manager.fail(task_id, TaskCancelledError(task_id))
            self._signal_scheduler()
            return
        if task.spec.actor_id is None and task.node_id is not None:
            # Dispatched to a node but possibly still in its dispatch
            # queue (burst-granted followers park there): a queued spec
            # cancels immediately, keeping the documented queued-task
            # semantics (reference: cancellation of leased-not-started
            # tasks).
            node = self.nodes.get(task.node_id)
            if node is not None and not getattr(node, "is_remote", False):
                spec = node.cancel_queued(task_id)
                if spec is not None:
                    self._release_task_resources(spec, task.node_id)
                    self._record_event(spec, "FAILED",
                                       node_id=task.node_id,
                                       error="cancelled")
                    self.task_manager.fail(
                        task_id, TaskCancelledError(task_id))
                    self._signal_scheduler()
                    return
            elif node is not None:
                # remote node: the daemon drops it from its queue and
                # reports back (TASK_CANCELLED_FWD); force also kills
                node.cancel_task(task_id, force=force)
                return
        if force:
            node_id = task.node_id
            if node_id is None and task.spec.actor_id is not None:
                info = self.actors.get(task.spec.actor_id)
                node_id = info.node_id if info else None
            node = self.nodes.get(node_id)
            if node is None:
                return
            if getattr(node, "is_remote", False):
                node.cancel_task(task_id)
                return
            with node._lock:
                for w in node._workers.values():
                    if task_id in w.running:
                        node.kill_worker(w.worker_id)
                        break

    def on_task_cancelled(self, node, spec: TaskSpec) -> None:
        """A node dropped a queued spec in response to cancel()."""
        from ray_tpu.exceptions import TaskCancelledError
        self._release_task_resources(spec, node.node_id)
        self._record_event(spec, "FAILED", node_id=node.node_id,
                           error="cancelled")
        self.task_manager.fail(spec.task_id,
                               TaskCancelledError(spec.task_id))
        self._signal_scheduler()

    def cluster_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for view in self.scheduler.snapshot().values():
            for k, v in view.total.items():
                totals[k] = totals.get(k, 0.0) + v
        return totals

    def available_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for view in self.scheduler.snapshot().values():
            for k, v in view.available.items():
                totals[k] = totals.get(k, 0.0) + v
        return totals

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    def put_function(self, function_id: str, blob: bytes) -> None:
        self.gcs.put_function(function_id, blob)

    def get_function(self, function_id: str):
        blob = self.gcs.get_function(function_id)
        return serialization.loads(blob) if blob else None

    def as_future(self, ref: ObjectRef):
        from concurrent.futures import Future
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def _record_event(self, spec: TaskSpec, state: str,
                      node_id: Optional[NodeID] = None,
                      error: Optional[str] = None,
                      worker_id=None, timestamp: Optional[float] = None,
                      duration: Optional[float] = None,
                      name: Optional[str] = None) -> None:
        if not get_config().task_events_enabled:
            return
        # Tuple layout (see Gcs.add_task_event): no dataclass
        # construction on the hot path.
        self.gcs.add_task_event((
            spec.task_id, name or spec.name or spec.function_id, state,
            time.time() if timestamp is None else timestamp,
            node_id, worker_id, error, duration, spec.parent_task_id,
            spec.trace_id))

    def _record_execution_events(self, spec: TaskSpec, node: Node,
                                 worker, msg: dict, state: str,
                                 error: Optional[str] = None,
                                 submitted_at: Optional[float] = None
                                 ) -> None:
        """Record worker-timed RUNNING + user PROFILE spans + the final
        state for one executed task (timestamps come from the worker so
        the timeline reflects true execution windows, reference:
        task_event_buffer.h:297 + profile_event.cc). All events for the
        task are appended under one GCS lock acquisition. Also feeds the
        built-in task latency histograms (queue / run / end-to-end)."""
        t_start, t_end = msg.get("t_start"), msg.get("t_end")
        if t_start is not None and t_end is not None:
            from ray_tpu.core.task_manager import (
                TASK_E2E_SECONDS, TASK_QUEUE_SECONDS, TASK_RUN_SECONDS)
            TASK_RUN_SECONDS.observe(max(0.0, t_end - t_start))
            if submitted_at is not None:
                TASK_QUEUE_SECONDS.observe(
                    max(0.0, t_start - submitted_at))
                TASK_E2E_SECONDS.observe(max(0.0, t_end - submitted_at))
        if not get_config().task_events_enabled:
            return
        worker_id = worker.worker_id if worker is not None else None
        name = spec.name or spec.function_id
        node_id = node.node_id
        parent = spec.parent_task_id
        trace_id = spec.trace_id
        events = []
        if t_start is not None:
            events.append((spec.task_id, name, "RUNNING", t_start,
                           node_id, worker_id, None,
                           (t_end - t_start) if t_end else None, parent,
                           trace_id))
        for span in msg.get("profile", ()):
            span_name, s0, s1 = span
            events.append((spec.task_id, span_name, "PROFILE", s0,
                           node_id, worker_id, None, s1 - s0, parent,
                           trace_id))
        events.append((spec.task_id, name, state,
                       time.time() if t_end is None else t_end,
                       node_id, worker_id, error, None, parent, trace_id))
        self.gcs.add_task_events(events)

    def shutdown(self) -> None:
        # Fold the lifetime ledger while worker journals and live-view
        # state are still current (stores close below); findings are
        # kept for post-shutdown refsan.report() calls.
        refsan.on_shutdown()
        # Same for the collective-program sanitizer: one fold over the
        # merged fingerprint journals, kept for collsan.report().
        from ray_tpu.devtools import collsan
        collsan.on_shutdown()
        # Stop the driver's sampler; park its counts in the store so
        # post-shutdown profile_dump()/profdiff captures still see it.
        from ray_tpu.devtools import profiler
        sampler = profiler.disable()
        if sampler is not None:
            profiler.store_push(sampler.label, sampler.counts,
                                sampler.samples, sampler.hz)
        _task_phase.reset()
        self._stopped.set()
        for hook in getattr(self, "_shutdown_hooks", ()):
            try:
                hook()
            except Exception:  # graftlint: disable=GL004
                pass  # teardown is best-effort; runtime is going away
        self._signal_scheduler()
        if self.head_server is not None:
            self.head_server.stop()
        if self.object_server is not None:
            self.object_server.stop()
        for node in list(self.nodes.values()):
            node.stop()
        self.nodes.clear()
        if self.gcs.store is not None:
            self.gcs.store.close()
        set_runtime(None)
