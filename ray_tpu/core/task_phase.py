"""Submit-path phase attribution: a per-task µs budget for the hot path.

BENCH_core.json says a trivial submit costs ~150 µs end to end; nothing
in the repo says where those µs go. This module brackets 1-in-N
submissions (``task_phase_sample_n``, recorder-on only) into a
contiguous chain of named flight-recorder spans:

    arg-serialize   value_to_arg over args/kwargs (remote())
    spec-build      registration + TaskSpec construction (remote())
    scheduler-queue submit entry -> lease acquisition (runtime)
    lease-dispatch  lease bookkeeping + node dispatch queue (node)
    frame-encode    serialization.dumps_fast of the wire frame (node)
    wire-write      socket handoff to the worker (node)
    worker-pickup   wire-write end -> worker ``t_start`` (on_task_done)
    execute         worker ``t_start`` -> ``t_end`` (informative)
    result-return   worker ``t_end`` -> driver completion processed

Each phase starts exactly where the previous one ended (``mark``
advances a per-task boundary), so a sampled task's lifetime is fully
tiled — gaps between instrumented call sites attribute to the adjacent
phase instead of vanishing. ``devtools/whereis.py --task-path`` folds
the events into the per-phase table; the union of the chains over the
bench window is the coverage figure the ≥85% acceptance bar checks.

Cost discipline (PERF.md): when the recorder is off, call sites gate on
``flight_recorder.RECORDER is not None`` or on the module-level
``_TRACKED`` dict being empty — two loads and a compare. For unsampled
tasks while a sampled chain is in flight, ``mark`` is one dict-get miss.
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from ray_tpu.core.config import get_config
from ray_tpu.util import flight_recorder as _flight

PHASES = ("arg-serialize", "spec-build", "scheduler-queue",
          "lease-dispatch", "frame-encode", "wire-write",
          "worker-pickup", "execute", "result-return")

# task_id -> last phase boundary (driver perf-ns). Driver-process only.
# Bounded: abandoned chains (client mode, dropped tasks) are cleared
# wholesale at the cap instead of LRU-tracked — sampling makes the dict
# tiny (in-flight sampled tasks only) so the cap is a leak backstop.
_TRACKED: Dict[object, int] = {}
_MAX_TRACKED = 4096
_counter = itertools.count()


def sample_begin() -> int:
    """Call at submit entry. Returns the chain-start ns when this
    submission is sampled (recorder on + 1-in-N), else 0."""
    if _flight.RECORDER is None:
        return 0
    n = get_config().task_phase_sample_n
    if n <= 0 or next(_counter) % n:
        return 0
    return _flight.clock_ns()


def begin_chain(task_id, t0_ns: int, t_args_done_ns: int) -> None:
    """Record the two submit-side phases remote() measured itself
    (args were converted before the spec existed, so the bracket is
    arg-serialize first, then spec-build) and start tracking."""
    rec = _flight.RECORDER
    if rec is None:
        return
    now = _flight.clock_ns()
    tag = {"task": task_id.hex()[:12]}
    rec.record("task_phase", "arg-serialize", t0_ns,
               t_args_done_ns - t0_ns, tag)
    rec.record("task_phase", "spec-build", t_args_done_ns,
               now - t_args_done_ns, tag)
    if len(_TRACKED) >= _MAX_TRACKED:
        _TRACKED.clear()
    _TRACKED[task_id] = now


def mark(task_id, phase: str) -> None:
    """Close the span from the task's last boundary to now under
    ``phase`` and advance the boundary. No-op (one dict-get miss) for
    untracked tasks — callers gate on ``_TRACKED`` being non-empty."""
    t0 = _TRACKED.get(task_id)
    if t0 is None:
        return
    rec = _flight.RECORDER
    if rec is None:           # recorder torn down mid-chain
        _TRACKED.pop(task_id, None)
        return
    now = _flight.clock_ns()
    rec.record("task_phase", phase, t0, now - t0,
               {"task": task_id.hex()[:12]})
    _TRACKED[task_id] = now


def finish(task_id, t_start_wall: Optional[float],
           t_end_wall: Optional[float]) -> None:
    """Close the chain at completion. The worker stamped ``t_start`` /
    ``t_end`` with time.time() (same machine); the flight anchor maps
    them into the driver perf-ns domain so worker-pickup / execute /
    result-return stay contiguous with the driver-side spans."""
    t0 = _TRACKED.pop(task_id, None)
    if t0 is None:
        return
    rec = _flight.RECORDER
    if rec is None:
        return
    now = _flight.clock_ns()
    tag = {"task": task_id.hex()[:12]}
    if t_start_wall is not None and t_end_wall is not None:
        wall_anchor, perf_anchor = _flight._get_anchor()
        s = perf_anchor + int((t_start_wall - wall_anchor) * 1e9)
        e = perf_anchor + int((t_end_wall - wall_anchor) * 1e9)
        # clamp into [t0, now]: wall/perf clock disagreement must not
        # produce negative spans or break chain contiguity
        s = min(max(s, t0), now)
        e = min(max(e, s), now)
        rec.record("task_phase", "worker-pickup", t0, s - t0, tag)
        rec.record("task_phase", "execute", s, e - s, tag)
        rec.record("task_phase", "result-return", e, now - e, tag)
    else:
        rec.record("task_phase", "result-return", t0, now - t0, tag)


def discard(task_id) -> None:
    """Drop a chain without recording (client mode hands the rest of
    the path to the head process, which can't see this task's entry)."""
    _TRACKED.pop(task_id, None)


def reset() -> None:
    """Test/bench hook: forget all in-flight chains."""
    _TRACKED.clear()
