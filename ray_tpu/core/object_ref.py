"""ObjectRef — a future for a (possibly remote) immutable object.

Capability parity with the reference's ObjectRef
(reference: python/ray/includes/object_ref.pxi; ownership model in
src/ray/core_worker/reference_counter.h:43): refs are created by task
submission or ``put``; holding a ref pins the object via the owner's
reference counter; refs are serializable and passable as task arguments
(dependency edges); dropping the last ref deletes the object.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: str = "driver",
                 _register: bool = True):
        self._id = object_id
        self._owner = owner
        self._registered = False
        if _register:
            from ray_tpu.core import runtime
            rt = runtime.get_runtime_or_none()
            if rt is not None:
                rt.reference_counter.add_local_reference(object_id)
                self._registered = True

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner(self) -> str:
        return self._owner

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Serialized refs re-register on deserialization (borrowing);
        # values being stored report contained refs for nested pinning.
        from ray_tpu.core import serialization
        serialization.note_ref(self._id)
        return (ObjectRef, (self._id, self._owner))

    def __del__(self):
        if self._registered:
            try:
                from ray_tpu.core import runtime
            except ImportError:
                return  # interpreter shutdown
            rt = runtime.get_runtime_or_none()
            if rt is not None:
                try:
                    rt.reference_counter.remove_local_reference(self._id)
                except Exception:  # graftlint: disable=GL004
                    pass  # __del__ during interpreter shutdown

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu.core import runtime
        return runtime.get_runtime().as_future(self)
