"""One selector IO loop per process for every control-plane socket.

Replaces the thread-per-connection reader design (client reader,
head accept + per-peer readers, object-server accept + per-pull
threads, per-Node selector threads) with a single epoll loop — the
analog of the reference's dedicated asio IO service threads
(client_connection.cc framing + boost::asio event loops).

Frame bytes are handled by one of two codecs, chosen per connection:

- ``_NativeCodec``: the C codec in native/src/wire.cc reached over
  ctypes. All recv/writev syscalls and frame memcpy run with the GIL
  released; outbound frames are coalesced into ~256KB blocks and
  flushed with one writev.
- ``_PyCodec``: pure-Python fallback (protocol.FrameReader +
  ``socket.sendmsg`` vectored flush) selected automatically when g++ /
  the native library is unavailable, or when ``RAY_TPU_NATIVE_WIRE=0``.

Backpressure: each connection has a bounded outbound queue
(``io_loop_high_water_bytes``); producer threads that outrun the
socket block on a drain event until the loop flushes the queue below
the low-water mark. The loop thread itself never blocks — bulk
transfers go through ``send_stream`` which pulls chunks only while the
queue has room.

Teardown discipline: all selector mutations and fd closes happen on
the loop thread (closing a registered fd from another thread can
deliver events for a recycled descriptor). ``on_close`` fires exactly
once per connection — for EOF, fatal errors, and explicit close().
"""

from __future__ import annotations

import ctypes
import logging
import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional

import heapq

from ray_tpu.core import protocol, serialization
from ray_tpu.core.config import get_config
from ray_tpu.devtools import locktrace, threadguard
from ray_tpu.native import _lib
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util.metrics import Gauge, Histogram

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
_RECV_CHUNK = 262144
_SENDMSG_IOV = 32

REGISTERED_FDS = Gauge(
    "ray_tpu_core_io_loop_registered_fds",
    "Sockets (connections + listeners) registered with the IO loop")
DISPATCH_SECONDS = Histogram(
    "ray_tpu_core_io_loop_dispatch_latency_seconds",
    "Frame-batch handler latency on the IO loop thread (sampled 1/64)",
    boundaries=[0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1])
QUEUE_DEPTH = Gauge(
    "ray_tpu_core_io_loop_outbound_queue_depth",
    "Peak outbound bytes queued across all loop connections (sampled ~1s)")
PROCESS_THREADS = Gauge(
    "ray_tpu_process_thread_count",
    "Live threads in this process (sampled ~1s by the IO loop)")

# Test hook: force the codec choice regardless of env/toolchain
# (None = automatic). The native choice still degrades to the
# fallback when the library can't be built.
_native_forced: Optional[bool] = None


def use_native_wire() -> bool:
    """True when new connections should use the C codec."""
    if _native_forced is not None:
        return bool(_native_forced) and _lib.try_load() is not None
    env = os.environ.get("RAY_TPU_NATIVE_WIRE", "1").strip().lower()
    if env in ("0", "false", "no", "off"):
        return False
    return _lib.try_load() is not None


# Chaos seam: when set, every new connection's codec is passed through
# this wrapper (devtools/chaos.py installs a fault-injecting shim that
# delays or drops inbound frames deterministically). Test-only — None
# in production, and the indirection costs one None-check per
# connection setup, never per frame.
_codec_wrapper = None


def _make_codec(native: Optional[bool] = None):
    if native is None:
        native = use_native_wire()
    if native:
        lib = _lib.try_load()
        if lib is not None:
            codec = _NativeCodec(lib)
        else:
            codec = _PyCodec()
    else:
        codec = _PyCodec()
    wrapper = _codec_wrapper
    if wrapper is not None:
        codec = wrapper(codec)
    return codec


class _NativeCodec:
    """Per-connection frame state in C (wire.cc). The decoder is only
    touched by the loop thread; the writer is internally mutexed so
    any thread may enqueue/flush. Handles are freed by GC (__del__),
    never eagerly: a racing sender thread may still hold a reference
    mid-call when the loop tears the connection down."""

    native = True

    def __init__(self, lib):
        self._lib = lib
        self._dec = lib.wire_decoder_new()
        self._wr = lib.wire_writer_new()

    def read(self, sock):
        lib = self._lib
        status = lib.wire_decoder_read_fd(self._dec, sock.fileno())
        frames = []
        ptr = ctypes.c_void_p()
        while True:
            n = lib.wire_decoder_next(self._dec, ctypes.byref(ptr))
            if n < 0:
                if n == _lib.WIRE_PROTO:
                    status = _lib.WIRE_PROTO
                break
            frames.append(ctypes.string_at(ptr, n))
        return frames, min(int(status), 0)

    def enqueue(self, payload: bytes) -> int:
        queued = self._lib.wire_writer_enqueue(self._wr, payload,
                                               len(payload))
        if queued < 0:
            raise OSError(f"frame too large ({len(payload)} bytes)")
        return int(queued)

    def flush(self, sock) -> int:
        try:
            fd = sock.fileno()
        except OSError:
            return _lib.WIRE_ERR
        if fd < 0:
            return _lib.WIRE_ERR
        return int(self._lib.wire_writer_flush_fd(self._wr, fd))

    def queued(self) -> int:
        return int(self._lib.wire_writer_queued(self._wr))

    def feed(self, data: bytes) -> None:
        self._lib.wire_decoder_feed(self._dec, bytes(data), len(data))

    def leftover(self) -> bytes:
        ptr = ctypes.c_void_p()
        n = self._lib.wire_decoder_leftover(self._dec, ctypes.byref(ptr))
        return ctypes.string_at(ptr, n) if n > 0 else b""

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is None:
            return
        if getattr(self, "_dec", None):
            lib.wire_decoder_free(self._dec)
        if getattr(self, "_wr", None):
            lib.wire_writer_free(self._wr)


class _PyCodec:
    """Pure-Python codec: FrameReader for inbound parsing and a deque
    of framed buffers flushed with ``socket.sendmsg`` (vectored write,
    the writev analog). Same interface and thread-safety contract as
    _NativeCodec."""

    native = False

    def __init__(self):
        self._reader = protocol.FrameReader()
        self._lock = locktrace.traced_lock("core.io_loop.pycodec")
        self._bufs: deque = deque()
        self._head = 0  # bytes of bufs[0] already sent
        self._queued = 0
        self._prefed: list = []  # frames injected via feed()

    def read(self, sock):
        reader = self._reader
        frames = []
        if self._prefed:
            with self._lock:
                frames, self._prefed = self._prefed, []
        status = 0
        while True:
            try:
                data = sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                status = _lib.WIRE_ERR
                break
            if not data:
                status = _lib.WIRE_EOF
                break
            frames.extend(reader.feed(data))
            if len(data) < _RECV_CHUNK:
                break
        return frames, status

    def enqueue(self, payload: bytes) -> int:
        buf = _LEN.pack(len(payload)) + payload
        with self._lock:
            self._bufs.append(buf)
            self._queued += len(buf)
            return self._queued

    def flush(self, sock) -> int:
        with self._lock:
            while self._bufs:
                iov = [memoryview(self._bufs[0])[self._head:]]
                for i in range(1, min(len(self._bufs), _SENDMSG_IOV)):
                    iov.append(self._bufs[i])
                try:
                    n = sock.sendmsg(iov)
                except (BlockingIOError, InterruptedError):
                    return self._queued
                except OSError:
                    return _lib.WIRE_ERR
                self._queued -= n
                while n > 0:
                    remain = len(self._bufs[0]) - self._head
                    if n >= remain:
                        n -= remain
                        self._head = 0
                        self._bufs.popleft()
                    else:
                        self._head += n
                        n = 0
            return 0

    def queued(self) -> int:
        with self._lock:
            return self._queued

    def feed(self, data: bytes) -> None:
        # Only runs before the connection is live (handshake leftover
        # bytes) — decoded frames are buffered for the next read().
        with self._lock:
            self._prefed.extend(self._reader.feed(bytes(data)))

    def leftover(self) -> bytes:
        return self._reader.leftover()


@threadguard.loop_owned("_streams", "_mask", "_registered")
class LoopConnection:
    """A framed connection serviced by the shared IO loop. Drop-in for
    protocol.MessageConnection on the send side (``send``/``close``/
    ``.sock``); inbound frames are pushed to the registered handler on
    the loop thread instead of being pulled by a reader thread."""

    def __init__(self, loop: "IOLoop", sock: socket.socket,
                 on_frames, on_close, *, label: str, high_water: int,
                 low_water: int, send_timeout: float,
                 native: Optional[bool] = None):
        self._loop = loop
        self.sock = sock
        self.label = label
        self._on_frames = on_frames
        self._on_close = on_close
        self._codec = _make_codec(native)
        self._high_water = high_water
        self._low_water = low_water
        self._send_timeout = send_timeout
        self._streams: deque = deque()
        self._drain = threading.Event()
        self._drain.set()
        self._torn = False
        self._closing = False
        self._registered = False
        self._mask = selectors.EVENT_READ
        self._flush_scheduled = False

    @property
    def native(self) -> bool:
        return self._codec.native

    @property
    def closed(self) -> bool:
        return self._torn or self._closing

    def send(self, msg: dict) -> None:
        protocol._maybe_chaos(msg.get("kind"))
        self.send_frame(serialization.dumps_fast(msg))

    def send_frame(self, payload: bytes) -> None:
        if self._torn or self._closing:
            raise OSError(f"connection closed ({self.label})")
        on_loop = self._loop.on_loop_thread()
        # Backpressure: producer threads (never the loop itself) wait
        # for the loop to drain the queue below the low-water mark.
        if not on_loop and self._codec.queued() >= self._high_water:
            self._wait_drain()
        self._codec.enqueue(bytes(payload))
        remaining = self._codec.flush(self.sock)
        if remaining < 0:
            self._loop._exec_on_loop(self._loop._teardown_conn, self)
            raise OSError(f"connection lost during send ({self.label})")
        if remaining > 0:
            if remaining >= self._high_water:
                self._drain.clear()
                # re-check: the loop may have flushed between our
                # flush and the clear — don't strand waiters
                if self._codec.queued() <= self._low_water:
                    self._drain.set()
            self._request_flush(on_loop)

    def send_stream(self, chunks: Iterator[bytes],
                    on_done: Optional[Callable] = None) -> None:
        """Queue a bulk byte-chunk stream (each chunk becomes one
        frame). The LOOP pulls chunks only while the outbound queue is
        below the low-water mark, so an arbitrarily large stream never
        blocks the loop or balloons memory. ``on_done(None)`` fires on
        completion, ``on_done(exc)`` on failure/teardown."""
        if self._torn or self._closing:
            raise OSError(f"connection closed ({self.label})")

        def _arm():
            if self._torn:
                IOLoop._stream_done(on_done,
                                    ConnectionError("connection closed"))
                return
            self._streams.append((chunks, on_done))
            self._loop._flush_conn(self)

        self._loop._exec_on_loop(_arm)

    def close(self) -> None:
        if self._torn or self._closing:
            return
        self._closing = True
        # Opportunistic final flush so a just-queued goodbye frame
        # (SHUTDOWN, CLIENT_DISCONNECT) reaches the peer before the
        # loop closes the socket.
        try:
            self._codec.flush(self.sock)
        except OSError:
            pass
        self._loop._exec_on_loop(self._loop._teardown_conn, self)

    def fileno(self) -> int:
        return self.sock.fileno()

    def queued_bytes(self) -> int:
        return self._codec.queued()

    def _wait_drain(self) -> None:
        deadline = time.monotonic() + self._send_timeout
        while not self._torn and self._codec.queued() >= self._high_water:
            self._drain.clear()
            if self._torn or self._codec.queued() < self._high_water:
                self._drain.set()
                break
            self._request_flush(False)
            waited = self._drain.wait(
                min(1.0, max(0.0, deadline - time.monotonic())))
            if not waited and time.monotonic() >= deadline:
                raise OSError(
                    f"send backpressure timeout ({self.label}, "
                    f"{self._codec.queued()} bytes queued)")
        if self._torn:
            raise OSError(f"connection closed ({self.label})")

    def _request_flush(self, on_loop: bool) -> None:
        if on_loop:
            self._loop._flush_conn(self)
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._loop._flush_conn, self)


class LoopListener:
    """A listening socket serviced by the loop: accepts on the loop
    thread and hands new sockets to ``on_accept(sock, addr)``."""

    def __init__(self, loop: "IOLoop", sock: socket.socket, on_accept,
                 label: str):
        self._loop = loop
        self.sock = sock
        self.label = label
        self._on_accept = on_accept
        self._torn = False
        self._closed_evt = threading.Event()

    def close(self, wait: bool = True) -> None:
        self._loop._exec_on_loop(self._loop._teardown_listener, self)
        if wait and not self._loop.on_loop_thread():
            self._closed_evt.wait(2.0)


@threadguard.loop_owned("_conns", "_listeners", "_peak_queued",
                         "_dispatch_n", "_last_housekeep")
class IOLoop:
    """The per-process selector loop. Use ``get_io_loop()`` for the
    shared singleton; tests may build private instances and stop()
    them. All selector mutations happen on the loop thread (via
    ``call_soon``); handler callbacks run on the loop thread and must
    not block."""

    def __init__(self, name: str = "rtpu-io-loop",
                 report_metrics: bool = False):
        self._selector = selectors.DefaultSelector()
        self._callbacks: deque = deque()
        self._timers = _Timers()
        self._conns: set = set()
        self._listeners: set = set()
        self._stopped = threading.Event()
        self._report_metrics = report_metrics
        self._dispatch_n = 0
        self._peak_queued = 0
        self._last_housekeep = 0.0
        waker_r, waker_w = socket.socketpair()
        waker_r.setblocking(False)
        waker_w.setblocking(False)
        self._waker_r, self._waker_w = waker_r, waker_w
        self._selector.register(waker_r, selectors.EVENT_READ,
                                ("waker", None))
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        # Opt-in runtime enforcement (RAY_TPU_THREADGUARD=1): a stall
        # watchdog samples this thread's stack when one dispatch pass
        # exceeds RAY_TPU_THREADGUARD_STALL_S.
        self._guard = (threadguard.LoopStallWatchdog(self._thread)
                       if threadguard.enabled() else None)
        self._thread.start()

    # ------------------------------------------------------------- API

    def on_loop_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def register(self, sock: socket.socket, on_frames,
                 on_close=None, *, label: str = "",
                 native: Optional[bool] = None,
                 high_water: Optional[int] = None,
                 low_water: Optional[int] = None) -> LoopConnection:
        """Adopt a connected socket; ``on_frames(conn, frames)`` runs
        on the loop thread for each batch of complete frames."""
        cfg = get_config()
        sock.setblocking(False)
        conn = LoopConnection(
            self, sock, on_frames, on_close, label=label, native=native,
            high_water=high_water or cfg.io_loop_high_water_bytes,
            low_water=low_water or cfg.io_loop_low_water_bytes,
            send_timeout=cfg.io_loop_send_timeout_s)
        self._exec_on_loop(self._do_register, conn)
        return conn

    def register_message_conn(self, sock: socket.socket, on_msg,
                              on_close=None, **kw) -> LoopConnection:
        """register() plus per-frame deserialization: ``on_msg(conn,
        msg_dict)``. One bad frame/handler is logged and skipped, not
        fatal to the connection."""

        def _on_frames(conn, frames):
            for frame in frames:
                try:
                    msg = serialization.loads(frame)
                except Exception:
                    logger.exception("io_loop: undecodable frame (%s)",
                                     conn.label)
                    continue
                try:
                    on_msg(conn, msg)
                except Exception:
                    logger.exception("io_loop: message handler error (%s)",
                                     conn.label)

        return self.register(sock, _on_frames, on_close, **kw)

    def register_listener(self, sock: socket.socket, on_accept,
                          label: str = "") -> LoopListener:
        sock.setblocking(False)
        lst = LoopListener(self, sock, on_accept, label)
        self._exec_on_loop(self._do_register_listener, lst)
        return lst

    def call_soon(self, fn, *args) -> None:
        """Run ``fn(*args)`` on the loop thread ASAP (thread-safe)."""
        self._callbacks.append((fn, args))
        if not self.on_loop_thread():
            self.wake()

    def call_later(self, delay: float, fn, *args) -> None:
        self._timers.add(time.monotonic() + delay, fn, args)
        if not self.on_loop_thread():
            self.wake()

    def wake(self) -> None:
        try:
            self._waker_w.send(b"\x00")
        except (BlockingIOError, InterruptedError):
            pass  # waker pipe already full -> loop is already waking
        except OSError:
            pass

    @threadguard.loop_only
    def detach(self, conn: LoopConnection) -> socket.socket:
        """Loop-thread only: unregister without closing the socket
        (protocol handoff, e.g. CAPI sessions). The caller owns the
        socket afterwards; on_close does NOT fire."""
        assert self.on_loop_thread()
        conn._torn = True
        conn._on_close = None
        if conn._registered:
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn._registered = False
        self._conns.discard(conn)
        self._update_fd_gauge()
        conn._drain.set()
        return conn.sock

    def barrier(self, timeout: float = 5.0) -> bool:
        """Block until the loop has processed everything queued before
        this call (test/diagnostic helper)."""
        if self.on_loop_thread():
            return True
        evt = threading.Event()
        self.call_soon(evt.set)
        return evt.wait(timeout)

    def stop(self) -> None:
        """Stop the loop and tear down every registered socket. Only
        for privately constructed loops (tests); the process singleton
        lives for the life of the process."""
        self._stopped.set()
        self.wake()
        if not self.on_loop_thread():
            self._thread.join(5.0)

    # ------------------------------------------------ loop internals

    def _exec_on_loop(self, fn, *args) -> None:
        if self.on_loop_thread():
            fn(*args)
        else:
            self.call_soon(fn, *args)

    @threadguard.loop_only
    def _do_register(self, conn: LoopConnection) -> None:
        if conn._torn or conn._closing:
            self._teardown_conn(conn)
            return
        try:
            self._selector.register(conn.sock, selectors.EVENT_READ,
                                    ("conn", conn))
        except (KeyError, ValueError, OSError):
            self._teardown_conn(conn)
            return
        conn._registered = True
        self._conns.add(conn)
        self._update_fd_gauge()
        if conn._codec.queued() or conn._streams:
            self._flush_conn(conn)

    @threadguard.loop_only
    def _do_register_listener(self, lst: LoopListener) -> None:
        if lst._torn:
            return
        try:
            self._selector.register(lst.sock, selectors.EVENT_READ,
                                    ("listener", lst))
        except (KeyError, ValueError, OSError):
            self._teardown_listener(lst)
            return
        self._listeners.add(lst)
        self._update_fd_gauge()

    @threadguard.loop_only
    def _run(self) -> None:
        guard = self._guard
        if guard:
            guard.enter()
        while not self._stopped.is_set():
            self._run_callbacks()
            timeout = 0.5
            deadline = self._timers.next_deadline()
            if deadline is not None:
                timeout = min(timeout,
                              max(0.0, deadline - time.monotonic()))
            if self._callbacks:
                timeout = 0.0
            if guard:
                guard.exit_busy()
            try:
                events = self._selector.select(timeout)
            except OSError:
                continue
            finally:
                if guard:
                    guard.enter()
            for key, mask in events:
                kind, obj = key.data
                try:
                    if kind == "waker":
                        self._drain_waker()
                    elif kind == "listener":
                        self._service_accept(obj)
                    else:
                        self._service_conn(obj, mask)
                except Exception:
                    logger.exception("io_loop: %s handler error", kind)
            now = time.monotonic()
            for fn, args in self._timers.pop_due(now):
                try:
                    fn(*args)
                except Exception:
                    logger.exception("io_loop: timer error")
            self._housekeep(now)
        self._finalize()
        if guard:
            guard.stop()

    def _run_callbacks(self) -> None:
        # Bounded drain: callbacks scheduled while running wait for
        # the next pass so socket events can't be starved.
        for _ in range(len(self._callbacks)):
            try:
                fn, args = self._callbacks.popleft()
            except IndexError:
                break
            try:
                fn(*args)
            except Exception:
                logger.exception("io_loop: callback error")

    def _drain_waker(self) -> None:
        try:
            # non-blocking socketpair: recv returns EAGAIN, never waits
            while self._waker_r.recv(4096):  # graftlint: disable=GL009
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    @threadguard.loop_only
    def _service_accept(self, lst: LoopListener) -> None:
        while True:
            try:
                # listener is non-blocking: accept never waits
                sock, addr = lst.sock.accept()  # graftlint: disable=GL009
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._teardown_listener(lst)
                return
            try:
                lst._on_accept(sock, addr)
            except Exception:
                logger.exception("io_loop: accept handler error (%s)",
                                 lst.label)
                try:
                    sock.close()
                except OSError:
                    pass

    @threadguard.loop_only
    def _service_conn(self, conn: LoopConnection, mask: int) -> None:
        if conn._torn:
            return
        if mask & selectors.EVENT_WRITE:
            self._flush_conn(conn)
            if conn._torn:
                return
        if mask & selectors.EVENT_READ:
            frames, status = conn._codec.read(conn.sock)
            if frames:
                self._dispatch(conn, frames)
            if status < 0:
                self._teardown_conn(conn)

    @threadguard.loop_only
    def _dispatch(self, conn: LoopConnection, frames) -> None:
        self._dispatch_n += 1
        timed = self._report_metrics and (self._dispatch_n & 63) == 0
        rec = _flight.RECORDER  # lock-free journal; no RPC (GL013)
        t0 = time.perf_counter() if timed else 0.0
        t0_ns = rec.clock() if rec is not None else 0
        try:
            conn._on_frames(conn, frames)
        except Exception:
            logger.exception("io_loop: frame handler error (%s)",
                             conn.label)
        if rec is not None:
            rec.record("io", "dispatch", t0_ns, rec.clock() - t0_ns,
                       {"conn": conn.label, "frames": len(frames)})
        if timed:
            # observe_local: a forwarding _record from the loop thread
            # would block on a reply only this thread can dispatch.
            DISPATCH_SECONDS.observe_local(time.perf_counter() - t0)

    @threadguard.loop_only
    def _flush_conn(self, conn: LoopConnection) -> None:
        if conn._torn:
            return
        conn._flush_scheduled = False
        remaining = conn._codec.flush(conn.sock)
        if remaining < 0:
            self._teardown_conn(conn)
            return
        # Pull stream chunks while there's room: the stream never
        # outruns the socket by more than ~low_water bytes.
        rec = _flight.RECORDER  # lock-free journal; no RPC (GL013)
        # not a retry loop: each except-continue pops the finished
        # stream first, so every re-entry makes progress
        while conn._streams and remaining < conn._low_water:  # graftlint: disable=GL019
            gen, on_done = conn._streams[0]
            t0_ns = rec.clock() if rec is not None else 0
            try:
                chunk = next(gen)
            except StopIteration:
                conn._streams.popleft()
                self._stream_done(on_done, None)
                continue
            except Exception as exc:
                conn._streams.popleft()
                self._stream_done(on_done, exc)
                continue
            if rec is not None:
                rec.record("io", "stream_chunk", t0_ns,
                           rec.clock() - t0_ns,
                           {"conn": conn.label, "bytes": len(chunk)})
            try:
                conn._codec.enqueue(bytes(chunk))
            except OSError as exc:
                conn._streams.popleft()
                self._stream_done(on_done, exc)
                self._teardown_conn(conn)
                return
            remaining = conn._codec.flush(conn.sock)
            if remaining < 0:
                self._teardown_conn(conn)
                return
        if remaining > self._peak_queued:
            self._peak_queued = remaining
        if remaining <= conn._low_water:
            conn._drain.set()
        self._set_write_interest(conn,
                                 remaining > 0 or bool(conn._streams))

    @threadguard.loop_only
    def _set_write_interest(self, conn: LoopConnection,
                            want: bool) -> None:
        if not conn._registered or conn._torn:
            return
        mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if want
                                       else 0)
        if mask == conn._mask:
            return
        try:
            self._selector.modify(conn.sock, mask, ("conn", conn))
            conn._mask = mask
        except (KeyError, ValueError, OSError):
            pass

    @threadguard.loop_only
    def _teardown_conn(self, conn: LoopConnection) -> None:
        if conn._torn:
            return
        conn._torn = True
        if conn._registered:
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn._registered = False
        self._conns.discard(conn)
        self._update_fd_gauge()
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        conn._drain.set()  # unblock backpressured senders -> they raise
        streams, conn._streams = list(conn._streams), deque()
        for gen, on_done in streams:
            try:
                gen.close()
            except Exception:
                logger.debug("io_loop: stream close error", exc_info=True)
            self._stream_done(
                on_done, ConnectionError(f"connection closed "
                                         f"({conn.label})"))
        if conn._on_close is not None:
            cb, conn._on_close = conn._on_close, None
            try:
                cb(conn)
            except Exception:
                logger.exception("io_loop: on_close error (%s)",
                                 conn.label)

    @threadguard.loop_only
    def _teardown_listener(self, lst: LoopListener) -> None:
        if lst._torn:
            lst._closed_evt.set()
            return
        lst._torn = True
        try:
            self._selector.unregister(lst.sock)
        except (KeyError, ValueError, OSError):
            pass
        self._listeners.discard(lst)
        self._update_fd_gauge()
        try:
            lst.sock.close()
        except OSError:
            pass
        lst._closed_evt.set()

    @staticmethod
    def _stream_done(on_done, exc) -> None:
        if on_done is None:
            return
        try:
            on_done(exc)
        except Exception:
            logger.exception("io_loop: stream completion callback error")

    def _update_fd_gauge(self) -> None:
        if self._report_metrics:
            REGISTERED_FDS.set_local(
                float(len(self._conns) + len(self._listeners)))

    def _housekeep(self, now: float) -> None:
        if now - self._last_housekeep < 1.0:
            return
        self._last_housekeep = now
        if not self._report_metrics:
            return
        total = 0
        for conn in self._conns:
            total += conn._codec.queued()
        QUEUE_DEPTH.set_local(float(max(total, self._peak_queued)))
        self._peak_queued = 0
        PROCESS_THREADS.set_local(float(threading.active_count()))

    @threadguard.loop_only
    def _finalize(self) -> None:
        for conn in list(self._conns):
            self._teardown_conn(conn)
        for lst in list(self._listeners):
            self._teardown_listener(lst)
        try:
            self._selector.close()
        except OSError:
            pass
        for s in (self._waker_r, self._waker_w):
            try:
                s.close()
            except OSError:
                pass


class _Timers:
    """Monotonic-deadline timer heap, mutated from any thread."""

    def __init__(self):
        self._lock = locktrace.traced_lock("core.io_loop.timers")
        self._heap: list = []
        self._seq = 0

    def add(self, when: float, fn, args) -> None:
        with self._lock:
            self._seq += 1
            heapq.heappush(self._heap, (when, self._seq, fn, args))

    def next_deadline(self) -> Optional[float]:
        with self._lock:
            return self._heap[0][0] if self._heap else None

    def pop_due(self, now: float):
        due = []
        with self._lock:
            while self._heap and self._heap[0][0] <= now:
                _, _, fn, args = heapq.heappop(self._heap)
                due.append((fn, args))
        return due


_singleton: Optional[IOLoop] = None
_singleton_lock = threading.Lock()


def get_io_loop() -> IOLoop:
    """The process-wide IO loop (started on first use, restarted if
    its thread ever died). This is the ONE socket-servicing thread the
    whole control plane shares."""
    global _singleton
    loop = _singleton
    if loop is not None and loop._thread.is_alive():
        return loop
    with _singleton_lock:
        if _singleton is None or not _singleton._thread.is_alive():
            _singleton = IOLoop(report_metrics=True)
        return _singleton
