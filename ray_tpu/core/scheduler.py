"""Cluster resource scheduler.

Capability parity with the reference's two-level scheduler
(reference: src/ray/raylet/scheduling/cluster_lease_manager.cc:196,
cluster_resource_scheduler.h:45, policy/hybrid_scheduling_policy.h:50,
policy/bundle_scheduling_policy.h): a cluster-wide resource view, a
hybrid pack-then-spread default policy, SPREAD / node-affinity /
node-label strategies, and atomic all-or-nothing placement-group bundle
reservation (reference: 2PC in gcs_placement_group_scheduler.h:281 —
here a single lock suffices because the scheduler is centralized in the
head process).

Resource demand that cannot be satisfied is queued; the per-node local
schedulers (ray_tpu/core/node.py) pull granted leases and dispatch to
workers. Demand summaries are exported for the autoscaler
(reference: gcs_autoscaler_state_manager.h:41).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.gcs import Bundle, Gcs, NodeRecord, PlacementGroupRecord
from ray_tpu.core.ids import NodeID, PlacementGroupID
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec
from ray_tpu.exceptions import PlacementGroupUnschedulableError
from ray_tpu.util.metrics import Gauge, Histogram

# Built-in scheduler instrumentation (reference: the reference exports
# scheduler stats through the metrics agent). Placement latency is
# observed at dispatch (TaskManager.mark_dispatched — every dispatch
# path funnels through it); queue depth is set once per scheduling pass.
PLACEMENT_LATENCY = Histogram(
    "ray_tpu_scheduler_placement_latency_seconds",
    "Time from task submission to dispatch onto a node",
    boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                30.0])
QUEUE_DEPTH = Gauge(
    "ray_tpu_scheduler_queue_depth",
    "Tasks parked in the scheduler backlog waiting for capacity")
INFEASIBLE_TASKS = Gauge(
    "ray_tpu_scheduler_infeasible_tasks",
    "Tasks whose resource request no node can ever satisfy")


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def _feasible(total: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(total.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


@dataclass
class NodeResources:
    total: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    queue_depth: int = 0  # leases granted but not yet finished


class ClusterScheduler:
    def __init__(self, gcs: Gcs):
        self._gcs = gcs
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, NodeResources] = {}
        self._rr_counter = 0
        # Outstanding leases keyed by caller token (task id). A tokened
        # release is idempotent: the completion path and the node-death
        # harvest can both observe the same task under a chaos drill
        # (TASK_DONE racing the heartbeat-miss kill), and only the first
        # credits the ledger. remove_node purges a node's tokens, so a
        # late by-id release after the id re-registers cannot credit the
        # NEW incarnation's ledger with capacity it never granted.
        self._leases: Dict[object, Tuple[NodeID, Dict[str, float]]] = {}

    # --- node membership ----------------------------------------------
    def add_node(self, node_id: NodeID, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._nodes[node_id] = NodeResources(
                total=dict(resources), available=dict(resources),
                labels=dict(labels or {}))

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            stale = [t for t, (nid, _) in self._leases.items()
                     if nid == node_id]
            for t in stale:
                del self._leases[t]

    def add_node_resources(self, node_id: NodeID, resources: Dict[str, float]) -> None:
        """Dynamically extend a node's totals (e.g. placement-group bundle
        resources materialize as `CPU_group_{pgid}` custom resources)."""
        with self._lock:
            view = self._nodes[node_id]
            for k, v in resources.items():
                view.total[k] = view.total.get(k, 0.0) + v
                view.available[k] = view.available.get(k, 0.0) + v

    def strip_node_resources(self, node_id: NodeID, keys: List[str]) -> None:
        with self._lock:
            view = self._nodes.get(node_id)
            if view is None:
                return
            for k in keys:
                view.total.pop(k, None)
                view.available.pop(k, None)

    # --- accounting ----------------------------------------------------
    def try_acquire(self, node_id: NodeID, need: Dict[str, float],
                    token: object = None) -> bool:
        with self._lock:
            view = self._nodes.get(node_id)
            if view is None or not _fits(view.available, need):
                return False
            for k, v in need.items():
                view.available[k] = view.available.get(k, 0.0) - v
            view.queue_depth += 1
            if token is not None:
                self._leases[token] = (node_id, dict(need))
            return True

    def release(self, node_id: NodeID, need: Dict[str, float],
                token: object = None) -> None:
        with self._lock:
            if token is not None:
                lease = self._leases.pop(token, None)
                if lease is None:
                    return  # already released, or purged by remove_node
                # Trust the ledger over the caller: release exactly what
                # was acquired, onto the node it was acquired from.
                node_id, need = lease
            view = self._nodes.get(node_id)
            if view is None:
                return
            for k, v in need.items():
                view.available[k] = min(view.total.get(k, 0.0),
                                        view.available.get(k, 0.0) + v)
            view.queue_depth = max(0, view.queue_depth - 1)

    def outstanding_leases(self, node_id: Optional[NodeID] = None) -> int:
        """Count of tokened leases (optionally for one node) — drill
        assertions use this to prove the ledger drains to zero."""
        with self._lock:
            if node_id is None:
                return len(self._leases)
            return sum(1 for nid, _ in self._leases.values()
                       if nid == node_id)

    def available(self, node_id: NodeID) -> Dict[str, float]:
        with self._lock:
            view = self._nodes.get(node_id)
            return dict(view.available) if view else {}

    def snapshot(self) -> Dict[NodeID, NodeResources]:
        with self._lock:
            return {
                nid: NodeResources(dict(v.total), dict(v.available),
                                   dict(v.labels), v.queue_depth)
                for nid, v in self._nodes.items()
            }

    # --- placement policy ----------------------------------------------
    def pick_node(self, spec: TaskSpec,
                  preferred: Optional[NodeID] = None) -> Optional[NodeID]:
        """Choose a node with resources available now; None if none can.

        Raises ValueError if no node is even *feasible* (infeasible task).
        """
        need = dict(spec.resources)
        strategy = spec.strategy
        if strategy.kind == "PLACEMENT_GROUP" and strategy.placement_group_id:
            need = _pg_resources(need, strategy.placement_group_id,
                                 strategy.bundle_index)
        with self._lock:
            candidates = list(self._nodes.items())
            if strategy.kind == "NODE_AFFINITY" and strategy.node_id is not None:
                view = self._nodes.get(strategy.node_id)
                if view is None or not _feasible(view.total, need):
                    if strategy.soft:
                        view = None  # fall through to the general policy
                    else:
                        # Target node is gone or can never fit the task.
                        raise ValueError(
                            f"hard NODE_AFFINITY target "
                            f"{strategy.node_id.hex()[:8]} is dead or "
                            f"infeasible for {need}")
                if view is not None:
                    if _fits(view.available, need):
                        return strategy.node_id
                    if not strategy.soft:
                        return None  # feasible but busy: wait for capacity
            if (strategy.kind == "NODE_ANTI_AFFINITY"
                    and strategy.node_id is not None):
                others = [(nid, v) for nid, v in candidates
                          if nid != strategy.node_id]
                if strategy.soft:
                    # Prefer other nodes; the avoided node stays eligible
                    # only when it is the sole feasible host.
                    if any(_feasible(v.total, need) for _, v in others):
                        candidates = others
                else:
                    candidates = others
            if strategy.kind == "NODE_LABEL" and strategy.labels:
                candidates = [
                    (nid, v) for nid, v in candidates
                    if all(v.labels.get(k) == val
                           for k, val in strategy.labels.items())
                ]
            feasible = [(nid, v) for nid, v in candidates if _feasible(v.total, need)]
            if not feasible:
                raise ValueError(
                    f"no feasible node for resources {need} "
                    f"(strategy {strategy.kind})")
            fitting = [(nid, v) for nid, v in feasible if _fits(v.available, need)]
            if not fitting:
                return None
            if strategy.kind == "SPREAD":
                self._rr_counter += 1
                fitting.sort(key=lambda kv: (kv[1].queue_depth, kv[0].hex()))
                return fitting[self._rr_counter % len(fitting)][0]
            # Hybrid default: pack onto the preferred (local) node until its
            # queue depth crosses the spread threshold, then least-loaded
            # (reference: hybrid_scheduling_policy.h:50).
            threshold = get_config().scheduler_spread_threshold
            if preferred is not None:
                for nid, v in fitting:
                    if nid == preferred and v.queue_depth <= max(
                            1, threshold * sum(v.total.get("CPU", 1) for _ in (0,))):
                        return nid
            fitting.sort(key=lambda kv: (kv[1].queue_depth, kv[0].hex()))
            return fitting[0][0]

    # --- placement groups ----------------------------------------------
    def reserve_placement_group(self, pg: PlacementGroupRecord) -> None:
        """Atomically reserve all bundles or raise (all-or-nothing).

        On success each bundle's resources are converted into
        pg-scoped custom resources (`{res}_group_{i}_{pgid}` and
        `{res}_group_{pgid}`) on the chosen node, mirroring the
        reference's bundle resource formatting
        (reference: src/ray/common/placement_group.h FormatPlacementGroupResource).
        """
        with self._lock:
            assignment = self._solve_bundles(pg)
            if assignment is None:
                raise PlacementGroupUnschedulableError(
                    f"cannot place bundles {[b.resources for b in pg.bundles]} "
                    f"with strategy {pg.strategy}")
            pgid = pg.pg_id.hex()
            for bundle, node_id in zip(pg.bundles, assignment):
                view = self._nodes[node_id]
                for k, v in bundle.resources.items():
                    view.available[k] -= v
                    view.total[k] -= v
                bundle.node_id = node_id
                wildcard = {f"{k}_group_{pgid}": v for k, v in bundle.resources.items()}
                indexed = {f"{k}_group_{bundle.index}_{pgid}": v
                           for k, v in bundle.resources.items()}
                self.add_node_resources(node_id, {**wildcard, **indexed})
            pg.state = "CREATED"

    def return_placement_group(self, pg: PlacementGroupRecord) -> None:
        """Release every reserved bundle. Idempotent: a second call
        (user remove racing the node-death re-pend under a drill) sees
        the bundles already cleared and no-ops, so pg-scoped resources
        are credited back exactly once per reservation."""
        with self._lock:
            if pg.state == "REMOVED":
                return
            pgid = pg.pg_id.hex()
            for bundle in pg.bundles:
                if bundle.node_id is None:
                    continue
                keys = [f"{k}_group_{pgid}" for k in bundle.resources]
                keys += [f"{k}_group_{bundle.index}_{pgid}" for k in bundle.resources]
                self.strip_node_resources(bundle.node_id, keys)
                view = self._nodes.get(bundle.node_id)
                if view is not None:
                    for k, v in bundle.resources.items():
                        view.total[k] = view.total.get(k, 0.0) + v
                        view.available[k] = view.available.get(k, 0.0) + v
                bundle.node_id = None
            pg.state = "REMOVED"

    def _solve_bundles(self, pg: PlacementGroupRecord) -> Optional[List[NodeID]]:
        """Greedy bundle placement honoring PACK/SPREAD/STRICT_* semantics
        (reference: policy/bundle_scheduling_policy.h:29,73,89)."""
        avail = {nid: dict(v.available) for nid, v in self._nodes.items()}
        node_labels = {nid: v.labels for nid, v in self._nodes.items()}
        nodes = list(avail.keys())
        result: List[NodeID] = []

        def labels_ok(nid: NodeID, bundle) -> bool:
            selector = getattr(bundle, "label_selector", None)
            if not selector:
                return True
            labels = node_labels.get(nid, {})
            return all(labels.get(k) == v for k, v in selector.items())

        def take(nid: NodeID, bundle) -> bool:
            if not labels_ok(nid, bundle):
                return False
            res = bundle.resources
            if not _fits(avail[nid], res):
                return False
            for k, v in res.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v
            return True

        if pg.strategy == "STRICT_PACK":
            for nid in nodes:
                trial = {k: dict(v) for k, v in avail.items()}
                ok = True
                for b in pg.bundles:
                    if not labels_ok(nid, b) or not _fits(
                            trial[nid], b.resources):
                        ok = False
                        break
                    for k, v in b.resources.items():
                        trial[nid][k] = trial[nid].get(k, 0.0) - v
                if ok:
                    return [nid] * len(pg.bundles)
            return None
        if pg.strategy == "STRICT_SPREAD":
            used: set = set()
            for b in pg.bundles:
                placed = False
                for nid in nodes:
                    if nid in used:
                        continue
                    if take(nid, b):
                        result.append(nid)
                        used.add(nid)
                        placed = True
                        break
                if not placed:
                    return None
            return result
        # PACK (soft-pack) and SPREAD (soft-spread)
        prefer_spread = pg.strategy == "SPREAD"
        for b in pg.bundles:
            order = sorted(
                nodes,
                key=lambda nid: (
                    (result.count(nid) if prefer_spread else -result.count(nid)),
                    nid.hex(),
                ),
            )
            placed = False
            for nid in order:
                if take(nid, b):
                    result.append(nid)
                    placed = True
                    break
            if not placed:
                return None
        return result


def _pg_resources(need: Dict[str, float], pg_id: PlacementGroupID,
                  bundle_index: int) -> Dict[str, float]:
    """Rewrite a resource request to target pg-scoped resources."""
    pgid = pg_id.hex()
    out = {}
    for k, v in need.items():
        if bundle_index >= 0:
            out[f"{k}_group_{bundle_index}_{pgid}"] = v
        else:
            out[f"{k}_group_{pgid}"] = v
    return out
