"""Client mode: a remote driver over TCP.

Capability parity with Ray Client (reference: python/ray/util/client/ —
a driver outside the cluster connects to a server over gRPC and proxies
init/remote/get/put/actor calls; server side holds the real driver
state). Here the head's existing TCP listener (remote_node.HeadServer)
accepts ``CLIENT_REGISTER`` sessions next to node daemons; the client
runtime speaks the same message vocabulary as a worker (GCS_REQUEST /
SUBMIT / GET_OBJECT / CHECK_READY / STREAM_NEXT / REF_ADD / REF_DROP),
so the whole public API — tasks, actors, named actors, streaming
generators, runtime envs, collectives rendezvous — works unchanged
from another host:

    ray_tpu.init(address="head-host:6379")   # client mode
    @ray_tpu.remote
    def f(x): ...

Object payloads: puts ship inline to the head (which stores them in
its arena and owns them on the client's behalf); gets return small
objects inline and large ones via a chunked pull from the holder
node's ObjectServer — tensor bytes never squeeze through the control
message stream.
"""

from __future__ import annotations

import logging
import threading

from ray_tpu.devtools import locktrace, refsan, threadguard
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core import task_phase as _task_phase
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.protocol import (
    PROTOCOL_VERSION, MessageConnection, connect_tcp, parse_address)
from ray_tpu.core.task_manager import ReferenceCounter
logger = logging.getLogger(__name__)

from ray_tpu.exceptions import (GetTimeoutError, HeadRestartedError,
                                ObjectLostError)


class _MemStore:
    """Minimal in-memory store satisfying object_transfer.pull_object's
    destination interface (the client has no shm arena)."""

    def __init__(self):
        self._bufs: Dict[ObjectID, bytearray] = {}
        self._sealed: Dict[ObjectID, threading.Event] = {}
        self._lock = locktrace.traced_lock("core.client.buffers")

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            event = self._sealed.get(oid)
        return event is not None and event.is_set()

    def create(self, oid: ObjectID, size: int) -> memoryview:
        with self._lock:
            if oid in self._bufs:
                raise FileExistsError(oid.hex())
            self._bufs[oid] = bytearray(size)
            self._sealed[oid] = threading.Event()
            return memoryview(self._bufs[oid])

    def seal(self, oid: ObjectID) -> None:
        self._sealed[oid].set()

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._bufs.pop(oid, None)
            self._sealed.pop(oid, None)

    def get_buffer(self, oid: ObjectID, timeout_s: float = 0.0):
        with self._lock:
            event = self._sealed.get(oid)
        if event is None or not event.wait(timeout_s):
            return None
        with self._lock:
            # a concurrent take() may have popped between the seal and
            # this read — caller treats None as "re-pull"
            buf = self._bufs.get(oid)
            return memoryview(buf) if buf is not None else None

    def release(self, oid: ObjectID) -> None:
        pass

    def take(self, oid: ObjectID) -> Optional[bytes]:
        """Pop the SEALED payload; None if a concurrent get consumed it
        or a re-pull is still in flight (caller re-pulls). Never hands
        out a partially-downloaded buffer."""
        with self._lock:
            event = self._sealed.get(oid)
            if event is None or not event.is_set():
                return None
            buf = self._bufs.pop(oid, None)
            self._sealed.pop(oid, None)
        return bytes(buf) if buf is not None else None


class ClientRuntime:
    """The runtime the public API talks to in client mode."""

    is_driver = False
    is_client = True

    def __init__(self, address: str, namespace: str = ""):
        self.address = address
        self.namespace = namespace
        self._req_lock = locktrace.traced_lock("core.client.req")
        # ObjectRefs minted before a head restart: the new head never
        # owned them, so gets fail fast with HeadRestartedError
        self._lost_oids: set = set()
        self._connected = threading.Event()
        self._connected.set()
        # Bumped by the reader at every disconnect; request() compares
        # it around send so a request that raced the inflight sweep
        # (registered after the sweep, sent into a dead socket) fails
        # typed instead of waiting forever for a reply.
        self._conn_epoch = 0
        self._req_counter = 0
        self._replies: Dict[int, Tuple[threading.Event, list]] = {}
        self._pubsub_callbacks: Dict[str, list] = {}
        self._closed = threading.Event()
        self._pull_store = _MemStore()
        self.current_runtime_env: Optional[dict] = None
        self.on_block = None  # worker-interface compat (never blocks a pool)
        self.reference_counter = ReferenceCounter()
        self.reference_counter.refsan_role = "borrower"
        self.reference_counter.set_on_first(
            lambda oid: self._send_borrow("REF_ADD", oid))
        self.reference_counter.set_deleter(
            lambda oid: self._send_borrow("REF_DROP", oid))
        # The blocking handshake runs on this thread; the registered
        # connection is then serviced by the shared IO loop (replies
        # and pubsub arrive via _on_msg — no dedicated reader thread).
        self._register_conn(self._connect())

    def _send_borrow(self, op: str, oid) -> None:
        """Report a borrow transition to the owner, mirrored into the
        refsan ledger (client events fold locally; the client has no
        push channel into the head's collector)."""
        led = refsan.LEDGER
        if led is not None:
            led.record(refsan.KIND_BORROW_SEND, oid.hex(), {"op": op})
        self._send({"kind": op, "object_id": oid.binary()})

    # -- transport -------------------------------------------------------
    def _register_conn(self, mconn: MessageConnection):
        from ray_tpu.core.io_loop import get_io_loop
        conn = get_io_loop().register_message_conn(
            mconn.sock, self._on_msg, self._on_conn_closed,
            label="client")
        self.conn = conn
        return conn
    def _connect(self) -> MessageConnection:
        """Dial + AUTH + CLIENT_REGISTER handshake (used at init and by
        the reconnect loop after a head restart)."""
        host, port = parse_address(self.address)
        conn = MessageConnection(connect_tcp(host, port, timeout=30.0))
        from ray_tpu.core.config import get_config
        token = get_config().auth_token
        if token:
            # plaintext auth frame BEFORE any pickled message (the head
            # refuses to unpickle from unauthenticated peers)
            from ray_tpu.core.protocol import send_frame
            send_frame(conn.sock, b"AUTH" + token.encode("utf-8"))
        from ray_tpu.core.protocol import PROTOCOL_MINOR
        conn.send({"kind": "CLIENT_REGISTER",
                   "proto_version": PROTOCOL_VERSION,
                   "proto_minor": PROTOCOL_MINOR,
                   "namespace": self.namespace})
        reply = conn.recv()
        if reply is None or reply.get("kind") != "REGISTERED":
            conn.close()
            reason = (reply or {}).get("reason", "connection closed")
            raise ConnectionError(f"head rejected client: {reason}")
        self.head_node_id = NodeID(reply["head_node_id"])
        # Negotiated head features (additive minors; protocol.py policy)
        self.head_proto_minor = reply.get("proto_minor", 0)
        self.head_capabilities = frozenset(reply.get("capabilities", ()))
        return conn

    def _send(self, msg: dict) -> None:
        if self._closed.is_set():
            return
        try:
            self.conn.send(msg)
        except OSError:
            pass  # the reader observes the drop and drives recovery

    def request(self, msg: dict, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core.config import get_config
        if not self._connected.is_set():
            # head link down: wait out an in-progress reconnect (bounded
            # by the window) instead of failing a brand-new request
            window = get_config().client_reconnect_s
            wait = window if timeout is None else min(timeout, window)
            if not self._connected.wait(wait) or self._closed.is_set():
                raise HeadRestartedError(
                    "connection to head lost (no reconnection within "
                    f"client_reconnect_s={window})")
        epoch = self._conn_epoch
        with self._req_lock:
            self._req_counter += 1
            rid = self._req_counter
            event = threading.Event()
            slot: list = [None]
            self._replies[rid] = (event, slot)
        msg["req_id"] = rid
        self._send(msg)
        if self._closed.is_set() or self._conn_epoch != epoch:
            # the reader's sweep only wakes requests registered at
            # disconnect time; one registered after (or sent into the
            # dying socket) must not wait on a reply that can never
            # arrive
            with self._req_lock:
                self._replies.pop(rid, None)
            raise HeadRestartedError("connection to head lost")
        if not event.wait(timeout):
            with self._req_lock:
                self._replies.pop(rid, None)
            raise GetTimeoutError(
                f"client request {msg.get('kind')} timed out")
        with self._req_lock:
            self._replies.pop(rid, None)
        if slot[0] is None:
            raise HeadRestartedError(
                "connection to head lost while waiting for a reply; "
                "in-flight work does not survive a head restart")
        return slot[0]

    def _fail_inflight(self) -> None:
        """Wake every pending request with 'reply lost' (slot stays
        None -> request() raises HeadRestartedError)."""
        with self._req_lock:
            entries = list(self._replies.values())
            self._replies.clear()
        for event, _slot in entries:
            event.set()

    def _try_reconnect(self) -> bool:
        """Re-register within client_reconnect_s after losing the head
        (head FT slice 2; reference: ray client reconnect_grace_period /
        workers reconnecting to a restarted GCS). Pre-restart
        ObjectRefs are recorded as lost — the new head never owned
        them — then the session resumes for NEW work."""
        from ray_tpu.core.config import get_config
        from ray_tpu.util.backoff import Backoff
        window = get_config().client_reconnect_s
        if window <= 0 or self._closed.is_set():
            return False
        # Jittered so a fleet of clients losing the same head does not
        # redial it in lockstep (util/backoff.py).
        backoff = Backoff(initial_s=0.25, max_s=2.0, deadline_s=window)
        while not self._closed.is_set():
            try:
                conn = self._connect()
            except (OSError, ConnectionError):
                # back off on the closed event (not time.sleep) so
                # close() interrupts the reconnect wait immediately
                if not backoff.wait(self._closed):
                    return False
                continue
            # every ref minted before the restart is gone for good.
            # Single-writer: only the reader thread reconnects, and
            # set.update is GIL-atomic for the racing readers.
            self._lost_oids.update(  # graftlint: disable=GL001
                self.reference_counter.live_object_ids())
            self._register_conn(conn)
            # re-establish server-side pubsub routes for live
            # subscriptions (the new head has no record of them)
            with self._req_lock:
                channels = [c for c, cbs in self._pubsub_callbacks.items()
                            if cbs]
            for channel in channels:
                self._send({"kind": "SUBSCRIBE", "channel": channel})
            self._connected.set()
            return True
        return False

    @threadguard.loop_only(loop_attr="conn._loop")
    def _on_msg(self, conn, msg: dict) -> None:
        """IO-loop handler for every head->client message (pubsub
        fanout + request/reply correlation)."""
        kind = msg.get("kind")
        if kind == "PUBSUB_MSG":
            for cb in list(self._pubsub_callbacks.get(
                    msg["channel"], ())):
                try:
                    cb(serialization.loads(msg["data"]))
                except Exception:
                    logger.exception("pubsub callback failed for "
                                     "channel %r", msg["channel"])
            return
        rid = msg.get("req_id")
        with self._req_lock:
            entry = self._replies.get(rid)
        if entry is not None:
            event, slot = entry
            slot[0] = msg
            event.set()

    @threadguard.loop_only(loop_attr="conn._loop")
    def _on_conn_closed(self, conn) -> None:
        """IO-loop teardown hook: fires exactly once per connection
        (EOF, error, or explicit close). Recovery — which dials the
        head with blocking IO — runs on a transient thread; the loop
        thread must not block."""
        current = getattr(self, "conn", None)
        if current is not None and conn is not current:
            return  # a stale pre-reconnect connection finished dying
        # single-writer: teardown fires once per connection, and the
        # replacement conn is only installed by the reconnect thread
        self._conn_epoch += 1  # graftlint: disable=GL001
        self._connected.clear()  # graftlint: disable=GL001
        self._fail_inflight()
        if self._closed.is_set():
            self._connected.set()
            return
        threading.Thread(target=self._reconnect_or_finalize,
                         name="client-reconnect", daemon=True).start()

    def _reconnect_or_finalize(self) -> None:
        if self._try_reconnect():
            return
        self._closed.set()
        self._connected.set()  # wake request() waiters to fail fast
        self._fail_inflight()

    # -- object plane ----------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        with serialization.collect_contained_refs() as contained:
            data, buffers = serialization.serialize(value)
        return self.put_serialized(
            data, buffers, contained=[o.binary() for o in contained])

    def put_serialized(self, data: bytes, buffers, contained=()) -> ObjectRef:
        packed = serialization.pack_parts(data, list(buffers))
        reply = self.request({"kind": "CLIENT_PUT", "data": packed,
                              "contained": list(contained)}, timeout=120.0)
        if reply.get("status") == "error":
            raise serialization.loads(reply["error"])
        oid = ObjectID(reply["object_id"])
        # constructing the ref registers the first local reference,
        # which sends REF_ADD — the head then holds the object for this
        # session until the matching REF_DROP
        return ObjectRef(oid)

    def _get_one(self, oid: ObjectID, timeout: Optional[float]):
        if oid in self._lost_oids:
            raise HeadRestartedError(
                f"ObjectRef {oid.hex()[:16]} was created before a head "
                "restart; objects do not survive one — resubmit the "
                "work that produced it")
        reply = self.request({"kind": "GET_OBJECT",
                              "object_id": oid.binary()}, timeout=timeout)
        status = reply["status"]
        if status == "inline":
            return serialization.unpack(reply["data"])
        if status == "pull":
            import time as _time

            from ray_tpu.core.object_transfer import get_pull_manager
            from ray_tpu.util.backoff import Backoff
            backoff = Backoff(initial_s=0.01, max_s=0.1)
            for _attempt in range(3):
                if not get_pull_manager().pull(tuple(reply["addr"]), oid,
                                               self._pull_store):
                    raise ObjectLostError(oid)
                data = self._pull_store.take(oid)
                if data is not None:
                    return serialization.unpack(data)
                # a concurrent get of the same ref consumed the buffer
                # between seal and take: pull again after a short
                # jittered pause (the peer needs time to re-seal)
                _time.sleep(backoff.next_delay())
            raise ObjectLostError(oid)
        if status == "error":
            raise serialization.loads(reply["error"])
        raise ObjectLostError(oid)

    def get(self, refs, timeout: Optional[float] = None):
        import time as _time
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        out = []
        for ref in refs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - _time.monotonic()))
            out.append(self._get_one(ref.id, remaining))
        return out[0] if single else out

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        # mirrors the worker's CHECK_READY polling protocol
        import time as _time
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds the number of refs")
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        pending = list(refs)
        # Pre-restart refs are permanently lost: count them READY (a
        # get on one raises HeadRestartedError, matching failed-object
        # wait semantics) instead of polling the new head forever.
        ready: List[ObjectRef] = [r for r in pending
                                  if r.id in self._lost_oids]
        pending = [r for r in pending if r.id not in self._lost_oids]
        while pending:
            ids = [r.id.binary() for r in pending]
            reply = self.request({"kind": "CHECK_READY",
                                  "object_ids": ids}, timeout=30.0)
            ready_set = set(reply["ready"])
            ready.extend(r for r in pending if r.id.binary() in ready_set)
            pending = [r for r in pending
                       if r.id.binary() not in ready_set]
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and _time.monotonic() >= deadline:
                break
            # readiness lives on the remote head, so this is a poll
            # interval, not a local condition — but waiting on _closed
            # keeps close() from blocking behind it
            if self._closed.wait(0.005):
                break
        done = ready[:num_returns]
        return done, ready[num_returns:] + pending

    # -- control plane ---------------------------------------------------
    def submit_spec(self, spec) -> None:
        if _task_phase._TRACKED:
            # Client mode records only the submit-side legs: the head
            # process owns scheduling/dispatch and cannot see this
            # process's sampled-chain table (core/task_phase.py).
            payload = serialization.dumps_fast(spec)
            _task_phase.mark(spec.task_id, "frame-encode")
            self._send({"kind": "SUBMIT", "spec": payload})
            _task_phase.mark(spec.task_id, "wire-write")
            _task_phase.discard(spec.task_id)
            return
        self._send({"kind": "SUBMIT",
                    "spec": serialization.dumps_fast(spec)})

    def create_actor(self, spec, name: Optional[str] = None) -> None:
        self.submit_spec(spec)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._send({"kind": "KILL_ACTOR", "actor_id": actor_id.binary(),
                    "no_restart": no_restart})

    def cancel(self, object_id: ObjectID, force: bool = False) -> None:
        self._send({"kind": "CANCEL", "object_id": object_id.binary(),
                    "force": force})

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: Optional[float]):
        reply = self.request({"kind": "STREAM_NEXT",
                              "task_id": task_id.binary(),
                              "index": index}, timeout=timeout)
        status = reply["status"]
        if status == "item":
            return "item", ObjectID(reply["object_id"])
        if status == "done":
            return "done", None
        return "error", serialization.loads(reply["error"])

    def gcs_call(self, method: str, *args, timeout: float = 30.0) -> Any:
        reply = self.request({"kind": "GCS_REQUEST", "method": method,
                              "args": serialization.dumps(args)},
                             timeout=timeout)
        if reply.get("error"):
            raise serialization.loads(reply["error"])
        return serialization.loads(reply["result"])

    def get_function(self, function_id: str):
        blob = self.gcs_call("get_function", function_id)
        if blob is None:
            raise RuntimeError(f"function {function_id} not found")
        return serialization.loads(blob)

    def put_function(self, function_id: str, blob: bytes) -> None:
        self.gcs_call("put_function", function_id, blob)

    def next_task_id(self) -> TaskID:
        return TaskID.from_random()

    def subscribe_channel(self, channel: str, callback) -> None:
        with self._req_lock:
            callbacks = self._pubsub_callbacks.setdefault(channel, [])
            first = not callbacks
            callbacks.append(callback)
        if first:
            self._send({"kind": "SUBSCRIBE", "channel": channel})

    def publish_channel(self, channel: str, message: Any) -> None:
        self.gcs_call("publish", channel, serialization.dumps(message))

    def as_future(self, ref: ObjectRef):
        """concurrent.futures bridge (reference: ObjectRef.future())."""
        from concurrent.futures import Future
        future: Future = Future()

        def resolve():
            try:
                future.set_result(self.get(ref))
            except Exception as exc:  # noqa: BLE001 — future carries it
                future.set_exception(exc)

        threading.Thread(target=resolve, daemon=True).start()
        return future

    def cluster_resources(self) -> Dict[str, float]:
        return self.gcs_call("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self.gcs_call("available_resources")

    def list_nodes(self) -> List[dict]:
        return self.gcs_call("list_nodes")

    def shutdown(self) -> None:
        self._closed.set()
        try:
            self.conn.send({"kind": "CLIENT_DISCONNECT"})
        except OSError:
            pass
        self.conn.close()
