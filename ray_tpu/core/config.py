"""Framework configuration flags, overridable via environment variables.

Capability parity with the reference's RAY_CONFIG macro system
(reference: src/ray/common/ray_config_def.h — 229 flags, env override
``RAY_<name>`` parsed in ray_config.cc). Here a flag declared as
``FLAG(name, default)`` is overridden by ``RTPU_<NAME>`` in the environment,
and a ``system_config`` dict can be passed to ``init()`` for per-session
overrides.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field, fields
from typing import Any


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(f"RTPU_{name.upper()}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    if isinstance(default, (list, dict)):
        return json.loads(raw)
    return raw


@dataclass
class Config:
    # --- object store ---
    # Bytes of shared memory for the node-local object store arena.
    object_store_memory: int = 256 * 1024 * 1024
    # Objects smaller than this are kept inline in the in-process memory
    # store / task replies instead of the shm store
    # (reference: max_direct_call_object_size, ray_config_def.h).
    max_inline_object_size: int = 100 * 1024
    # Seconds between eviction scans when the store is under pressure.
    object_store_full_retry_s: float = 0.05
    object_store_full_max_retries: int = 100

    # Size budget for the node-local cache of extracted runtime_env
    # packages and pip venvs (reference: uri_cache.py default 10 GiB).
    runtime_env_cache_bytes: int = 10 * 1024 * 1024 * 1024
    # Per-worker log file rotation threshold (one .1 backup kept; 0
    # disables rotation).
    worker_log_max_bytes: int = 64 * 1024 * 1024

    # --- workers / scheduling ---
    # Max workers a node's pool will fork (0 => num_cpus).
    max_workers_per_node: int = 0
    # Idle workers kept warm for reuse (reference: worker_pool prestart).
    min_idle_workers: int = 1
    worker_start_timeout_s: float = 30.0
    # Queue-depth threshold at which the hybrid policy spills to other nodes
    # (reference: scheduler_spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Same-shape plain-CPU specs dispatched per scheduler acquisition
    # (lease-reuse burst; the node worker cap bounds real concurrency).
    scheduler_burst_grant: int = 16
    # Max consecutive task retries on worker failure.
    task_max_retries: int = 3
    # Polling interval of the node-manager control loops.
    control_loop_interval_s: float = 0.005

    # --- actors ---
    actor_default_max_restarts: int = 0
    actor_method_default_max_task_retries: int = 0

    # --- health / failure detection ---
    health_check_interval_s: float = 0.5
    health_check_failure_threshold: int = 5
    # Grace period before a dead worker's in-flight tasks are failed.
    worker_death_grace_s: float = 0.5

    # --- core IO loop ---
    # Outbound queue bytes above which producer threads block (write
    # backpressure) until the loop drains the connection below the
    # low-water mark; bulk streams self-pace on the same marks
    # (reference: client_connection.cc async write queue).
    io_loop_high_water_bytes: int = 4 * 1024 * 1024
    io_loop_low_water_bytes: int = 1024 * 1024
    # Max seconds a backpressured sender waits before the send fails.
    io_loop_send_timeout_s: float = 60.0

    # --- multi-host control plane ---
    # TCP port for the head's node-daemon listener: -1 disables the
    # listener (single-host mode), 0 picks a free port
    # (reference: gcs_server port + raylet node_manager_port).
    head_port: int = -1
    head_host: str = "127.0.0.1"
    # Remote-node heartbeat cadence and declared-dead threshold
    # (reference: gcs_health_check_manager.h:45).
    heartbeat_interval_s: float = 0.5
    heartbeat_timeout_s: float = 5.0
    # Chunk size for node-to-node object transfer (reference: chunked
    # push/pull, object_manager.proto:63-66).
    object_chunk_size: int = 1024 * 1024
    # Seconds a node daemon keeps retrying its head connection after
    # losing it (head crash/restart) before giving up and exiting
    # (reference: raylets reconnecting to a restarted GCS,
    # gcs_init_data.cc replay). 0 = exit immediately (legacy behavior).
    # The daemon re-registers under its same node id; work in flight
    # across the outage is lost and re-driven by the new head's driver.
    node_reconnect_s: float = 0.0
    # Seconds a CLIENT driver keeps retrying its head connection after
    # losing it (head crash/restart). In-flight requests still fail
    # with HeadRestartedError (pre-restart ObjectRefs are gone — the
    # new head never owned them) but the session re-registers and new
    # submissions work. 0 = fail permanently (legacy behavior).
    client_reconnect_s: float = 0.0
    # Shared-secret authentication for cross-host connections
    # (reference: src/ray/rpc/authentication/ — cluster-wide token).
    # When set on the head (RTPU_AUTH_TOKEN), peers must open with a
    # plaintext AUTH frame carrying the same token — validated BEFORE
    # the head deserializes anything from the connection (pickle from
    # an unauthenticated peer would be code execution). Empty = open
    # cluster (the default, matching the reference's default).
    auth_token: str = ""
    # Max concurrent inbound pulls an object server admits
    # (reference: pull_manager.h:50 admission control).
    object_pull_concurrency: int = 8
    # Puller-side in-flight byte budget shared by all concurrent pulls
    # in one process (reference: push_manager.h:28 in-flight chunk
    # limit). A lone pull may exceed it so oversize objects still move.
    object_pull_inflight_bytes: int = 256 * 1024 * 1024

    # --- virtual nodes (chaos-plane scale-out; core/virtual_node.py) ---
    # In-process lightweight nodes that register over the head's real
    # TCP listener but execute tasks on one shared thread pool and
    # heartbeat via IO-loop timers, so head-node threads stay O(1) in
    # node count (64-128 virtual nodes on one box for envelope drills).
    # Per-virtual-node object store capacity (plain bytearrays, not
    # shm) — small by default so spill paths exercise under drills.
    virtual_node_store_bytes: int = 8 * 1024 * 1024
    # Task-execution threads SHARED by every virtual node in a pool.
    virtual_node_executor_threads: int = 8

    # --- GCS durability ---
    # Journal file for control-plane state (KV, jobs, functions): a new
    # head started with the same path replays it (reference:
    # Redis-backed GCS fault tolerance, redis_store_client.h). Empty
    # disables persistence.
    gcs_persistence_path: str = ""

    # --- lineage / spilling ---
    # Completed stateless task specs retained for object reconstruction
    # (reference: max_lineage_bytes, task_manager.h:184). 0 disables.
    lineage_max_entries: int = 10_000
    # Spill referenced objects to disk when the shm arena is full
    # (reference: local_object_manager.h:43 + external_storage.py).
    object_spill_enabled: bool = True

    # --- logging / events ---
    task_events_enabled: bool = True
    task_events_buffer_size: int = 100_000
    # Cluster lifecycle event plane (core/events.py): node/worker/actor
    # transitions, lease grants, reconstruction spans — always-on and
    # cheap (one tuple append under the GCS lock per event). The buffer
    # bounds GCS memory; recovery_report() and the /api/events surfaces
    # read from it.
    cluster_events_enabled: bool = True
    cluster_events_buffer_size: int = 100_000
    log_to_driver: bool = True
    # Distinct traces retained in the GCS trace store — LRU-evicted by
    # last-span arrival time so a loadgen run can't grow the store
    # without bound. Spans per trace are bounded separately.
    trace_store_max_traces: int = 512
    trace_store_max_spans: int = 4096

    # --- flight recorder (util/flight_recorder.py) ---
    # Per-process ring-buffer event journal + driver-side collector;
    # off by default — when off the instrumentation hot paths cost two
    # loads and a compare.
    flight_recorder_enabled: bool = False
    # Event slots preallocated per process (ring wraps, newest wins).
    flight_recorder_capacity: int = 4096
    # Cadence of the worker flusher thread (clock ping-pong + journal
    # increment push over the control channel).
    flight_flush_interval_s: float = 0.2

    # --- perf observatory (devtools/profiler.py, core/task_phase.py) ---
    # Submit-path phase attribution: when the flight recorder is on,
    # 1-in-N submissions get their full spec-build → result-return
    # chain bracketed into ``task_phase`` events (whereis --task-path
    # folds them into a per-phase µs budget). 0 disables sampling.
    task_phase_sample_n: int = 64
    # Sampling profiler wall-clock rate. The profiler itself is gated
    # by the RAY_TPU_PROFILER env (not config: it must be inheritable
    # by spawned workers before any config exists), like refsan.
    profiler_hz: int = 101
    # Cadence of the worker-side profile push to the driver store.
    profiler_push_interval_s: float = 1.0

    # --- refsan (devtools/refsan.py) ---
    # Hostile-store mode for the object-lifetime sanitizer: collapse
    # the owner's borrow grace window to ~0 so deferred reclaims fire
    # at the earliest legal moment. Stress tests combine it with
    # RAY_TPU_REFSAN / RAY_TPU_REFSAN_CANARY (env, not config: the
    # ledger must gate before any config exists) to force
    # evict-under-borrow races deterministically.
    refsan_hostile_eviction: bool = False

    # --- rpc chaos (fault injection; reference: rpc_chaos.h) ---
    # JSON map of "method" -> failure probability in [0,1].
    testing_rpc_failure: dict = field(default_factory=dict)
    testing_delay_us: int = 0

    def __post_init__(self):
        for f in fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def apply_overrides(self, overrides: dict | None):
        if not overrides:
            return
        for key, value in overrides.items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown config flag: {key}")
            setattr(self, key, value)


_config_lock = threading.Lock()
_config: Config | None = None


def auth_token_matches(supplied) -> bool:
    """Constant-time check of a peer-supplied token (bytes or str)
    against the configured cluster token. The ONE comparison both the
    pickle and C-API handshake paths use — always over bytes, so
    non-ASCII tokens or garbage peer input can't raise out of the
    session thread (hmac.compare_digest on str is ASCII-only)."""
    import hmac
    required = get_config().auth_token.encode("utf-8")
    if supplied is None:
        supplied = b""
    elif isinstance(supplied, str):
        supplied = supplied.encode("utf-8", "replace")
    elif not isinstance(supplied, (bytes, bytearray)):
        return False
    return hmac.compare_digest(bytes(supplied), required)


def get_config() -> Config:
    # Lock-free fast path: config objects are immutable after
    # reset_config; rebinding a module global is atomic under the GIL
    # and this is called on every dispatch/completion.
    global _config
    config = _config
    if config is not None:
        return config
    with _config_lock:
        if _config is None:
            _config = Config()
        return _config


def reset_config(overrides: dict | None = None) -> Config:
    global _config
    with _config_lock:
        _config = Config()
        _config.apply_overrides(overrides)
        return _config
