"""@remote functions — task submission frontend.

Capability parity with the reference's RemoteFunction
(reference: python/ray/remote_function.py:313 _remote — serialize args,
register the function in the GCS function store once, build a TaskSpec,
submit via the core worker; options() for per-call overrides).
"""

from __future__ import annotations

import hashlib
import threading

from ray_tpu.devtools import locktrace
import weakref
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization
from ray_tpu.core import task_phase as _task_phase
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import Arg, SchedulingStrategy, TaskSpec

# Bound lazily on first use: remote_function is imported during package
# init before ray_tpu.core.runtime finishes loading.
_runtime_get = None


def _get_runtime():
    global _runtime_get
    if _runtime_get is None:
        from ray_tpu.core.runtime import get_runtime
        _runtime_get = get_runtime
    return _runtime_get()


def resources_from_options(options: Dict[str, Any],
                           default_cpu: float = 1.0) -> Dict[str, float]:
    resources = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    resources["CPU"] = float(default_cpu if num_cpus is None else num_cpus)
    if resources["CPU"] == 0:
        resources.pop("CPU")
    num_tpus = options.get("num_tpus")
    if num_tpus:
        validate_tpu_quantity(float(num_tpus))
        resources["TPU"] = float(num_tpus)
    elif resources.get("TPU"):
        validate_tpu_quantity(float(resources["TPU"]))
    memory = options.get("memory")
    if memory:
        resources["memory"] = float(memory)
    return resources


def validate_tpu_quantity(quantity: float) -> None:
    """Whole-chip TPU requests must be a supported partition size: the
    visibility env plumbing only has bounds configs for 1, 2, 4, and 8
    chips (reference: TPU_VALID_CHIP_OPTIONS + validate_resource_
    request_quantity, _private/accelerators/tpu.py:270). Fractional
    requests (<1) share a host and are always allowed."""
    if quantity < 1:
        return
    if quantity not in (1.0, 2.0, 4.0, 8.0):
        raise ValueError(
            f"requested TPU={quantity} is not a supported chip "
            "configuration; supported: fractional (<1), 1, 2, 4, 8")


def submitting_task_id(rt):
    """TaskID of the task currently executing in this process (None on
    the driver) — recorded as the child's parent for timeline tracing."""
    local = getattr(rt, "_current_task_id", None)
    return getattr(local, "value", None) if local is not None else None


def submitting_trace_context():
    """(trace_id, parent_span_id) to stamp into a spec: the active
    trace context if one exists (inside a traced task, serve hop, or a
    user ``tracing.span()``), else a freshly minted root — every task
    tree is retrievable by trace_id."""
    from ray_tpu.util import tracing
    ctx = tracing.get_trace_context()
    if ctx is None:
        return tracing.new_trace_id(), None
    return ctx.trace_id, ctx.span_id


def strategy_from_options(options: Dict[str, Any]) -> SchedulingStrategy:
    strategy = options.get("scheduling_strategy")
    if strategy is None:
        return SchedulingStrategy()
    if isinstance(strategy, str):
        return SchedulingStrategy(kind=strategy)
    return strategy  # already a SchedulingStrategy (or PG strategy adapter)


def value_to_arg(value: Any, runtime) -> Arg:
    """Convert one call argument into a TaskSpec Arg.

    ObjectRefs become dependency edges; small values inline into the spec;
    large values are put into the object store and passed by reference
    (reference: task_submission/dependency_resolver.h:35 inlining rules).
    """
    if isinstance(value, ObjectRef):
        arg = Arg(object_id=value.id)
        arg._keepalive = value  # pin: the spec holds the ref until done
        return arg
    # Serialize under a ref collector so ObjectRefs *embedded* in the
    # argument are containment-pinned for the life of the spec — without
    # this, a caller dropping its handle while the task is queued deletes
    # the inner object before execution (reference: reference_counter.h
    # nested "contained in" tracking).
    with serialization.collect_contained_refs() as contained:
        data, buffers = serialization.serialize(value)
    pins = [ObjectRef(oid) for oid in contained]
    if not buffers and len(data) <= get_config().max_inline_object_size:
        arg = Arg(value_bytes=serialization.pack_parts(data, buffers))
        if pins:
            arg._keepalive = pins
        return arg
    ref = runtime.put_serialized(data, buffers)
    arg = Arg(object_id=ref.id)
    # pin until the spec (and thus the arg) is dropped
    arg._keepalive = (ref, pins) if pins else ref
    return arg


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        self._lock = locktrace.traced_lock("core.remote_function")
        self._blob: Optional[bytes] = None
        self._function_id: Optional[str] = None
        self._registered_with = None  # weakref.ref to the runtime
        # Options are immutable per RemoteFunction (options() clones):
        # precompute the per-call constants off the submit hot path.
        self._resources = resources_from_options(self._options)
        self._strategy = strategy_from_options(self._options)
        self._name = (self._options.get("name")
                      or getattr(fn, "__qualname__", ""))
        self._norm_env = None
        # weakref, not id(): a recycled id() after shutdown()+init()
        # would serve kv:// URIs never uploaded to the new cluster
        self._norm_env_with = None

    def _resolve_runtime_env(self, rt):
        """Normalized runtime env for this call: the explicit option
        (packaged once per runtime — uploads are content-addressed so
        re-normalizing after re-init is cheap) merged over the
        submitting worker's own env (child tasks inherit)."""
        explicit = self._options.get("runtime_env")
        inherited = getattr(rt, "current_runtime_env", None)
        if explicit is None and not inherited:
            return (None, "")  # hot path: no env anywhere
        from ray_tpu.runtime_env import (merge_runtime_envs,
                                         normalize_runtime_env,
                                         runtime_env_hash)
        if explicit is not None:
            with self._lock:
                cached_rt = (self._norm_env_with()
                             if self._norm_env_with is not None else None)
                if cached_rt is not rt:
                    self._norm_env = normalize_runtime_env(explicit, rt)
                    self._norm_env_with = weakref.ref(rt)
                explicit = self._norm_env
        env = merge_runtime_envs(inherited, explicit)
        return (env, runtime_env_hash(env)) if env else (None, "")

    @property
    def options_dict(self):
        return self._options

    def _ensure_registered(self, runtime) -> str:
        with self._lock:
            if self._blob is None:
                self._blob = serialization.dumps(self._fn)
                name = getattr(self._fn, "__qualname__", "fn")
                digest = hashlib.sha1(self._blob).hexdigest()[:24]
                self._function_id = f"fn:{name}:{digest}"
            cached = (self._registered_with()
                      if self._registered_with is not None else None)
            if cached is not runtime:  # weakref: id() could be recycled
                runtime.put_function(self._function_id, self._blob)
                self._registered_with = weakref.ref(runtime)
            return self._function_id

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        clone = RemoteFunction(self._fn, merged)
        clone._blob = self._blob
        clone._function_id = self._function_id
        return clone

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference:
        python/ray/dag — FunctionNode via .bind)."""
        from ray_tpu.dag.node import FunctionNode
        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        rt = _get_runtime()
        # Sampled submit-path attribution (core/task_phase.py): args are
        # converted before the spec so the arg-serialize leg brackets
        # cleanly; recorder-off cost is two loads and a compare.
        t_phase = (_task_phase.sample_begin()
                   if _flight.RECORDER is not None else 0)
        task_args = [value_to_arg(a, rt) for a in args]
        task_kwargs = {k: value_to_arg(v, rt) for k, v in kwargs.items()}
        t_args_done = _flight.clock_ns() if t_phase else 0
        function_id = self._ensure_registered(rt)
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        # "streaming": incremental yields via ObjectRefGenerator
        # (reference: num_returns="streaming", _raylet.pyx:299).
        if num_returns == "streaming":
            num_returns = -1
        renv, renv_hash = self._resolve_runtime_env(rt)
        trace_id, parent_span_id = submitting_trace_context()
        spec = TaskSpec(
            task_id=rt.next_task_id(),
            function_id=function_id,
            args=task_args,
            kwargs=task_kwargs,
            num_returns=num_returns,
            resources=dict(self._resources),
            strategy=self._strategy,
            max_retries=opts.get("max_retries", get_config().task_max_retries),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            name=self._name,
            runtime_env=renv,
            runtime_env_hash=renv_hash,
            parent_task_id=submitting_task_id(rt),
            trace_id=trace_id,
            parent_span_id=parent_span_id,
        )
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        if t_phase:
            _task_phase.begin_chain(spec.task_id, t_phase, t_args_done)
        rt.submit_spec(spec)
        if num_returns == -1:
            from ray_tpu.core.generator import ObjectRefGenerator
            return ObjectRefGenerator(spec.task_id)
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "remote functions cannot be called directly; use .remote()")

    def __reduce__(self):
        # Remote functions close over locks/caches; reconstruct from the
        # wrapped function + options so they serialize into closures
        # (e.g. a task that submits further tasks).
        return (RemoteFunction, (self._fn, self._options))
