"""Head-side proxy for node daemons on other hosts.

Capability parity with the reference's head-of-cluster view of remote
raylets (reference: src/ray/gcs/gcs_node_manager.h:47 node table +
gcs_health_check_manager.h:45 liveness; node_manager gRPC client in
src/ray/raylet_rpc_client/). A ``RemoteNode`` presents the same surface
the scheduler and runtime use on in-process ``Node`` objects
(``dispatch``, ``dispatch_to_actor``, ``kill_worker``, ``store.delete``)
but forwards each call over the daemon's TCP control connection
(``ray_tpu/core/node_daemon.py`` is the other end). Large objects never
transit this connection: they move node-to-node through the chunked
object servers (object_transfer.py).

``HeadServer`` is the head's TCP listener: it accepts daemon
connections, registers them with the runtime, and runs one reader
thread per daemon that translates forwarded worker traffic into the
same runtime handler calls an in-process node would make.
"""

from __future__ import annotations

import threading

from ray_tpu.devtools import locktrace, threadguard
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.protocol import MessageConnection, listen_tcp
from ray_tpu.core.task_spec import TaskSpec


class RemoteWorkerStub:
    """Stands in for a WorkerHandle living in a daemon process: ``send``
    routes the payload through the daemon, which forwards it to the
    worker's local socket."""

    def __init__(self, node: "RemoteNode", worker_id: WorkerID):
        self.node = node
        self.worker_id = worker_id

    def send(self, msg: dict) -> bool:
        return self.node.send({"kind": "TO_WORKER",
                               "worker_id": self.worker_id.binary(),
                               "payload": msg})


class RemoteStoreProxy:
    """The slice of the store interface the head invokes on other nodes.
    Reads go through the object servers, never through this proxy."""

    def __init__(self, node: "RemoteNode"):
        self._node = node

    def delete(self, object_id: ObjectID) -> None:
        self._node.send({"kind": "DELETE_OBJECT",
                         "object_id": object_id.binary()})


class RemoteNode:
    proto_minor = 0  # negotiated at NODE_REGISTER

    is_remote = True

    def __init__(self, runtime, conn: MessageConnection, node_id: NodeID,
                 resources: Dict[str, float], labels: Dict[str, str],
                 object_addr: Tuple[str, int], address: str):
        self.runtime = runtime
        self.conn = conn
        self.node_id = node_id
        self.resources = dict(resources)
        self.labels = dict(labels)
        self.object_addr = tuple(object_addr)
        self.address = address
        self.store = RemoteStoreProxy(self)
        self.session_dir = None
        self.last_heartbeat = time.time()
        # open NODE_HEARTBEAT_MISS event seq (None = no miss episode);
        # a NODE_DEAD for this node chains to it as its cause
        self._hb_miss_seq = None
        self.idle_workers = 0
        self.store_used = 0
        self._alive = True
        self._dead_lock = locktrace.traced_lock("core.remote_node.dead")
        # Tasks dispatched to this node and not yet completed; on node
        # death these are retried/failed exactly like worker crashes
        # (the daemon can no longer report them).
        self._inflight_lock = locktrace.traced_lock("core.remote_node.inflight")
        self._inflight: Dict[TaskID, TaskSpec] = {}

    # --- liveness ------------------------------------------------------
    @property
    def alive(self) -> bool:
        return self._alive

    def mark_dead(self) -> bool:
        """Test-and-set: returns True for exactly one caller (the one
        that must run the death cleanup — EOF reader and heartbeat
        monitor can race here)."""
        with self._dead_lock:
            was = self._alive
            self._alive = False
            return was

    def send(self, msg: dict) -> bool:
        if not self._alive:
            return False
        try:
            self.conn.send(msg)
            return True
        except OSError:
            return False

    # --- inflight bookkeeping -----------------------------------------
    def track(self, spec: TaskSpec) -> None:
        with self._inflight_lock:
            self._inflight[spec.task_id] = spec

    def untrack(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._inflight_lock:
            return self._inflight.pop(task_id, None)

    def take_inflight(self) -> List[TaskSpec]:
        with self._inflight_lock:
            specs = list(self._inflight.values())
            self._inflight.clear()
            return specs

    # --- Node interface used by the runtime/scheduler ------------------
    def dispatch(self, spec: TaskSpec) -> None:
        self.track(spec)
        if not self.send({"kind": "DISPATCH",
                          "spec": serialization.dumps_fast(spec)}):
            # Leave the spec tracked: the death sweep (take_inflight)
            # is what retries it.
            self.runtime.on_remote_node_death(self.node_id, expected=self)
            # Late-track race: if the death harvest already ran (we lost
            # the mark_dead race, or the id was re-taken), the call above
            # no-ops and the spec tracked above was missed — reap it.
            leftovers = self.take_inflight()
            if leftovers:
                self.runtime.reap_node_specs(self, leftovers)

    def dispatch_to_actor(self, worker_id: WorkerID, spec: TaskSpec) -> bool:
        self.track(spec)
        ok = self.send({"kind": "DISPATCH_ACTOR",
                        "worker_id": worker_id.binary(),
                        "spec": serialization.dumps_fast(spec)})
        if not ok:
            self.untrack(spec.task_id)
        return ok

    def kill_worker(self, worker_id: WorkerID) -> None:
        self.send({"kind": "KILL_WORKER", "worker_id": worker_id.binary()})

    def prestart_workers(self, count: int, profile: str = "cpu") -> None:
        self.send({"kind": "PRESTART", "count": count, "profile": profile})

    def cancel_task(self, task_id: TaskID, force: bool = True) -> None:
        self.send({"kind": "CANCEL_TASK", "task_id": task_id.binary(),
                   "force": force})

    def idle_worker_count(self) -> int:
        return self.idle_workers

    def stop(self) -> None:
        self.send({"kind": "STOP"})
        self.close()

    def close(self) -> None:
        self.mark_dead()
        try:
            self.conn.close()
        except OSError:
            pass


class ClientSession:
    """A remote-driver session on the head's TCP listener
    (reference: python/ray/util/client/server/ — the server-side proxy
    holding real driver state for an out-of-cluster client). Plays the
    roles the runtime handlers expect of a (node, worker) pair:
    ``is_remote=True`` so object replies use inline data or chunked
    pulls, never local-shm pointers."""

    is_remote = True
    object_addr = None
    proto_minor = 0  # negotiated at CLIENT_REGISTER

    def __init__(self, runtime, conn: MessageConnection):
        self.runtime = runtime
        self.conn = conn
        self.node_id = NodeID.from_random()   # identity only; never
        self.worker_id = WorkerID.from_random()  # scheduled onto
        self.held_refs: set = set()
        self._lock = locktrace.traced_lock("core.remote_node")

    def send(self, msg: dict) -> bool:
        try:
            self.conn.send(msg)
            return True
        except OSError:
            return False

    def handle(self, msg: dict) -> bool:
        rt = self.runtime
        kind = msg["kind"]
        if kind == "CLIENT_DISCONNECT":
            return False
        if kind == "GCS_REQUEST":
            rt.handle_gcs_request(self, msg)
        elif kind == "SUBMIT":
            rt.submit_spec(serialization.loads(msg["spec"]))
        elif kind == "CLIENT_PUT":
            self._client_put(msg)
        elif kind == "GET_OBJECT":
            rt.handle_get_object(self, self, msg)
        elif kind == "CHECK_READY":
            rt.handle_check_ready(self, msg)
        elif kind == "STREAM_NEXT":
            rt.handle_stream_next(self, msg)
        elif kind == "SUBSCRIBE":
            rt.handle_subscribe(self, self, msg)
        elif kind == "REF_ADD":
            oid = ObjectID(msg["object_id"])
            with self._lock:
                self.held_refs.add(oid)
            rt.reference_counter.add_local_reference(oid)
        elif kind == "REF_DROP":
            oid = ObjectID(msg["object_id"])
            with self._lock:
                self.held_refs.discard(oid)
            rt.deferred_remove_reference(oid)
        elif kind == "KILL_ACTOR":
            rt.kill_actor(ActorID(msg["actor_id"]),
                          no_restart=msg.get("no_restart", True))
        elif kind == "CANCEL":
            rt.cancel(ObjectID(msg["object_id"]),
                      force=msg.get("force", False))
        else:
            # Additive evolution (protocol.py policy): a newer-minor
            # client probing a kind this head predates must get a
            # definitive answer, not a request that never resolves.
            if msg.get("req_id") is not None:
                self.send({"kind": "UNSUPPORTED",
                           "req_id": msg["req_id"],
                           "unsupported_kind": kind})
        return True

    def _client_put(self, msg: dict) -> None:
        """Store a client-shipped payload on the head (owner side), pin
        it for this session, and reply with the assigned object id."""
        rt = self.runtime
        oid = ObjectID.from_random()
        out = {"kind": "OBJECT_VALUE", "req_id": msg.get("req_id"),
               "object_id": oid.binary()}
        try:
            rt.store_packed_object(oid, msg["data"],
                                   contained=msg.get("contained", ()))
        except Exception as exc:  # noqa: BLE001 — e.g. arena full
            out.update(status="error",
                       error=serialization.dumps(exc))
            self.send(out)
            return
        # no pin here: the client's ObjectRef construction sends REF_ADD
        # on this same ordered connection right after the reply — a
        # second pin would leak one count forever
        out["status"] = "stored"
        self.send(out)

    def close(self) -> None:
        """Client disconnected: release every reference it held —
        objects it exclusively pinned become reclaimable — and drop its
        pubsub push routes (they capture this dead connection)."""
        with self._lock:
            held = list(self.held_refs)
            self.held_refs.clear()
        for oid in held:
            self.runtime.reference_counter.remove_local_reference(oid)
        self.runtime._drop_worker_subscriptions(self.node_id)


class _HeadConn:
    """Per-peer protocol state machine on the head, driven by the IO
    loop (replaces the thread-per-connection reader). The first frame
    decides the peer's codec: C-API clients open with the b"CAPI"
    magic (binary TLV, any language — handed off to a dedicated
    session thread since that protocol is blocking); everything else
    is a pickled dict (nodes, Python clients) behind the AUTH gate."""

    def __init__(self, server: "HeadServer", sock):
        self.server = server
        self.runtime = server.runtime
        self.state = "first"
        self.node: Optional[RemoteNode] = None
        self.client: Optional["ClientSession"] = None
        self.conn = server._io.register(sock, self._on_frames,
                                        self._on_close,
                                        label="head-peer")
        with server._conns_lock:
            server._conns.add(self.conn)
        if server._stopped.is_set():
            self.conn.close()

    @threadguard.loop_only(loop_attr="server._io")
    def _on_frames(self, conn, frames) -> None:
        for idx, frame in enumerate(frames):
            if self.state == "steady":
                self._handle_frame(frame)
                continue
            action = self._handshake(frame)
            if action == "capi":
                self._handoff_capi(frame, frames[idx + 1:])
                return
            if action == "close":
                conn.close()
                return

    def _handshake(self, frame: bytes) -> Optional[str]:
        from ray_tpu.core.config import auth_token_matches, get_config
        if self.state == "first":
            if frame[:4] == b"CAPI":
                # C-API peers authenticate inside their own (binary,
                # never-unpickled) handshake.
                return "capi"
            self.state = "register"
            # Auth gate BEFORE any unpickling: deserializing bytes
            # from an unauthenticated peer would be arbitrary code
            # execution (pickle). With a token configured, the first
            # frame must be the plaintext b"AUTH" + token; only then
            # is the next frame parsed (reference:
            # rpc/authentication/ token middleware).
            if get_config().auth_token:
                if (frame[:4] != b"AUTH"
                        or not auth_token_matches(frame[4:])):
                    try:
                        self.conn.send_frame(serialization.dumps_fast(
                            {"kind": "REGISTER_REJECTED",
                             "reason": "authentication failed"}))
                    except OSError:
                        pass
                    return "close"
                return None  # token consumed; next frame registers
            if frame[:4] == b"AUTH":
                # peer supplies a token the head doesn't require: accept
                return None
            return self._register(frame)
        return self._register(frame)

    def _register(self, frame: bytes) -> Optional[str]:
        try:
            msg = serialization.loads(frame)
        except Exception:  # noqa: BLE001 — garbage frame (port probe,
            # mis-pointed client): close instead of leaking the socket
            return "close"
        try:
            from ray_tpu.core.protocol import (
                CAPABILITIES, PROTOCOL_MINOR, PROTOCOL_VERSION)
            kind = msg.get("kind")
            peer_version = msg.get("proto_version", 0)
            if kind not in ("NODE_REGISTER", "CLIENT_REGISTER"):
                return "close"
            # Major must match; minor may differ (additive-only
            # evolution — see protocol.py policy).
            if peer_version != PROTOCOL_VERSION:
                self.conn.send({"kind": "REGISTER_REJECTED",
                                "reason": "protocol version mismatch: "
                                          f"head={PROTOCOL_VERSION} "
                                          f"peer={peer_version}"})
                return "close"
            handshake_extra = {
                "proto_version": PROTOCOL_VERSION,
                "proto_minor": PROTOCOL_MINOR,
                "capabilities": list(CAPABILITIES),
            }
            if kind == "CLIENT_REGISTER":
                self.client = ClientSession(self.runtime, self.conn)
                self.client.proto_minor = msg.get("proto_minor", 0)
                self.conn.send({"kind": "REGISTERED",
                                "head_node_id":
                                    self.runtime.head_node_id.binary(),
                                **handshake_extra})
            else:
                self.node = self.runtime.register_remote_node(self.conn,
                                                              msg)
                # negotiation is two-way: record the peer's minor so a
                # newer head can gate additive kinds per node
                self.node.proto_minor = msg.get("proto_minor", 0)
                self.conn.send({"kind": "REGISTERED", **handshake_extra})
            self.state = "steady"
        except Exception:  # noqa: BLE001 — keep the daemon link alive
            import traceback
            traceback.print_exc()
        return None

    def _handle_frame(self, frame: bytes) -> None:
        try:
            msg = serialization.loads(frame)
            if self.client is not None:
                if not self.client.handle(msg):
                    self.conn.close()
            else:
                self.server._handle(self.node, msg)
        except Exception:  # noqa: BLE001 — keep the daemon link alive
            import traceback
            traceback.print_exc()

    def _handoff_capi(self, first: bytes, rest) -> None:
        # Re-frame frames the loop already decoded past the magic plus
        # the partial tail, so the CAPI session sees every byte.
        from ray_tpu.core.protocol import _LEN, _PrebufferedSocket
        leftover = b"".join(_LEN.pack(len(f)) + f for f in rest)
        leftover += self.conn._codec.leftover()
        sock = self.server._io.detach(self.conn)
        with self.server._conns_lock:
            self.server._conns.discard(self.conn)
        sock.setblocking(True)
        if leftover:
            sock = _PrebufferedSocket(sock, leftover)

        def _serve():
            from ray_tpu.capi import CapiSession
            CapiSession(self.runtime, sock, first).serve()

        threading.Thread(target=_serve, name="capi-session",
                         daemon=True).start()

    def _on_close(self, conn) -> None:
        with self.server._conns_lock:
            self.server._conns.discard(conn)
        if self.node is not None:
            # expected= pins the death to THIS connection's RemoteNode:
            # with node_reconnect_s the daemon may have re-registered
            # on a new connection before this (stale) one's EOF was
            # observed, and a by-id kill would tear down the fresh
            # record.
            self.runtime.on_remote_node_death(self.node.node_id,
                                              expected=self.node)
        if self.client is not None:
            self.client.close()


class HeadServer:
    """The head's TCP listener for node daemons."""

    def __init__(self, runtime, host: str, port: int):
        self.runtime = runtime
        self._listener = listen_tcp(host, port)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._stopped = threading.Event()
        # Every accepted connection, so stop() can sever them the way a
        # real head crash would (clients/daemons then observe EOF and
        # run their reconnect paths instead of waiting forever).
        self._conns_lock = locktrace.traced_lock("core.remote_node.conns")
        self._conns: set = set()
        # Accepts and per-peer reads ride the shared IO loop — no
        # accept thread, no thread per peer (io_loop.py).
        from ray_tpu.core.io_loop import get_io_loop
        self._io = get_io_loop()
        self._listener_handle = self._io.register_listener(
            self._listener, self._on_accept, label="head")
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="head-monitor", daemon=True)
        self._monitor_thread.start()

    def _on_accept(self, sock, _addr) -> None:
        _HeadConn(self, sock)

    def _monitor_loop(self) -> None:
        """Declare remote nodes dead when heartbeats stop
        (reference: gcs_health_check_manager.h:45)."""
        cfg = get_config()
        while not self._stopped.wait(cfg.heartbeat_interval_s):
            now = time.time()
            for node in list(self.runtime.nodes.values()):
                if not (isinstance(node, RemoteNode) and node.alive):
                    continue
                overdue = now - node.last_heartbeat
                if overdue > cfg.heartbeat_timeout_s:
                    # On a starved box (or when the timeout is barely
                    # over 2 intervals) the monitor's first wake past
                    # the miss threshold can already be past the death
                    # threshold, skipping the miss episode entirely and
                    # leaving the NODE_DEAD incident without its
                    # precursor. Open the episode first — never widen
                    # drill tolerances to paper over the gap.
                    self._note_heartbeat_miss(node, overdue)
                    self.runtime.on_remote_node_death(node.node_id,
                                                      expected=node)
                elif overdue > 2 * cfg.heartbeat_interval_s:
                    self._note_heartbeat_miss(node, overdue)

    def _note_heartbeat_miss(self, node: RemoteNode,
                             overdue: float) -> None:
        """Once per miss episode: the seq rides the node so a later
        NODE_DEAD chains to it (gcs.mark_node_dead reads _hb_miss_seq);
        a fresh HEARTBEAT clears it. A chaos-injected fault (freeze
        drill) becomes the episode's cause when one is pending."""
        if getattr(node, "_hb_miss_seq", None) is not None:
            return
        node._hb_miss_seq = self.runtime.gcs.add_cluster_event(
            "NODE_HEARTBEAT_MISS", "WARNING", node_id=node.node_id,
            caused_by=getattr(node, "_chaos_cause_seq", None),
            message=f"last heartbeat {overdue:.2f}s ago")

    def _handle(self, node: RemoteNode, msg: dict) -> None:
        rt = self.runtime
        kind = msg["kind"]
        if kind == "HEARTBEAT":
            node.last_heartbeat = time.time()
            node._hb_miss_seq = None  # miss episode over
            node.idle_workers = msg.get("idle", 0)
            node.store_used = msg.get("store_used", 0)
        elif kind == "TASK_DONE_FWD":
            spec: TaskSpec = serialization.loads(msg["spec"])
            node.untrack(spec.task_id)
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.on_task_done(node, worker, spec, msg["msg"])
        elif kind == "WORKER_CRASHED_FWD":
            running = [serialization.loads(s) for s in msg["running"]]
            for spec in running:
                node.untrack(spec.task_id)
            actor_id = (ActorID(msg["actor_id"])
                        if msg.get("actor_id") else None)
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.on_worker_crashed(node, worker, running, actor_id)
        elif kind == "ACTOR_DISPATCH_FAILED":
            spec = serialization.loads(msg["spec"])
            node.untrack(spec.task_id)
            rt._route_actor_task(spec)
        elif kind == "TASK_CANCELLED_FWD":
            # daemon dropped a node-queued spec on cancel: fail the ref
            spec = serialization.loads(msg["spec"])
            node.untrack(spec.task_id)
            rt.on_task_cancelled(node, spec)
        elif kind == "SUBMIT":
            rt.submit_spec(serialization.loads(msg["spec"]))
        elif kind == "PUT_META":
            rt.on_worker_put(node, msg)
        elif kind == "STREAM_ITEM":
            rt.on_stream_item(node, msg)
        elif kind == "STREAM_NEXT":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_stream_next(worker, msg)
        elif kind == "REPLICA":
            rt.add_object_replica(ObjectID(msg["object_id"]), node.node_id)
        elif kind == "GET_OBJECT":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_get_object(node, worker, msg)
        elif kind == "CHECK_READY":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_check_ready(worker, msg)
        elif kind == "SUBSCRIBE":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_subscribe(node, worker, msg)
        elif kind == "SPILL_REQUEST":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_spill_request(node, worker, msg)
        elif kind == "SPILLED":
            rt.on_objects_spilled(node, msg)
        elif kind == "GCS_REQUEST":
            worker = RemoteWorkerStub(node, WorkerID(msg["worker_id"]))
            rt.handle_gcs_request(worker, msg)
        elif kind == "KILL_ACTOR":
            rt.kill_actor(ActorID(msg["actor_id"]),
                          no_restart=msg.get("no_restart", True))
        elif kind == "REF_ADD":
            rt.reference_counter.add_local_reference(ObjectID(msg["object_id"]))
        elif kind == "REF_DROP":
            oid = ObjectID(msg["object_id"])
            if msg.get("defer", True):
                rt.deferred_remove_reference(oid)
            else:
                rt.reference_counter.remove_local_reference(oid)
        elif kind == "CANCEL":
            rt.cancel(ObjectID(msg["object_id"]),
                      force=msg.get("force", False))
        elif kind == "UNSUPPORTED":
            pass  # peer's answer to OUR probe; NEVER re-answered (an
            # UNSUPPORTED->UNSUPPORTED echo would loop forever)
        else:
            # Additive wire-schema evolution: a newer-minor peer may
            # send kinds this head predates. Probes carrying a req_id
            # get a definitive UNSUPPORTED answer (so the peer can fall
            # back) instead of a silent drop or a crash (protocol.py
            # evolution policy; reference: proto3 unknown-field
            # tolerance + capability probing).
            if msg.get("req_id") is not None:
                node.send({"kind": "UNSUPPORTED",
                           "req_id": msg["req_id"],
                           "unsupported_kind": kind})

    def stop(self) -> None:
        self._stopped.set()
        # The loop's non-blocking listener closes synchronously — no
        # wake-connection hack needed (the old accept-thread design
        # had to dial itself to unblock accept() before closing).
        self._listener_handle.close(wait=True)
        # Sever every accepted connection, as a real crash would —
        # remote peers (clients, daemons) observe EOF and run their
        # reconnect logic instead of waiting on a half-dead head.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
