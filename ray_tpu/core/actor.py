"""Actor classes, handles, and methods.

Capability parity with the reference's actor frontend
(reference: python/ray/actor.py — ActorClass:1188, ActorClass._remote:1498,
ActorMethod:583, ActorHandle:1857): ``@remote`` classes gain
``.remote(...)`` construction and per-method ``.remote()`` invocation;
handles serialize (pass actors to tasks/other actors); named actors are
retrievable via ``get_actor`` (reference: python/ray/_private/worker.py
get_actor); ``max_restarts`` enables GCS-driven restart
(reference: gcs_actor_manager.cc restart path).
"""

from __future__ import annotations

import hashlib
import inspect
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import (
    resources_from_options,
    strategy_from_options,
    submitting_task_id,
    submitting_trace_context,
    value_to_arg,
)
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, max_task_retries: int = 0):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._max_task_retries = max_task_retries

    def options(self, **overrides) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            num_returns=overrides.get("num_returns", self._num_returns),
            max_task_retries=overrides.get("max_task_retries",
                                           self._max_task_retries))

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference:
        python/ray/dag — ClassMethodNode via .bind)."""
        from ray_tpu.dag.node import ClassMethodNode
        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def remote(self, *args, **kwargs):
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        num_returns = self._num_returns
        if num_returns == "streaming":
            # incremental yields (reference: _raylet.pyx:299)
            num_returns = -1
        trace_id, parent_span_id = submitting_trace_context()
        spec = TaskSpec(
            task_id=rt.next_task_id(),
            function_id="",
            args=[value_to_arg(a, rt) for a in args],
            kwargs={k: value_to_arg(v, rt) for k, v in kwargs.items()},
            num_returns=num_returns,
            resources={},
            max_retries=self._max_task_retries,
            name=f"{self._handle._class_name}.{self._method_name}",
            actor_id=self._handle._actor_id,
            method_name=self._method_name,
            seq_no=self._handle._next_seq(),
            parent_task_id=submitting_task_id(rt),
            trace_id=trace_id,
            parent_span_id=parent_span_id,
        )
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        rt.submit_spec(spec)
        if num_returns == -1:
            from ray_tpu.core.generator import ObjectRefGenerator
            return ObjectRefGenerator(spec.task_id)
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError("actor methods cannot be called directly; use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 method_names: List[str]):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_names = list(method_names)
        self._seq_lock = threading.Lock()
        self._seq = 0

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def __getattr__(self, name: str):
        if name == "__ray_call__":
            # escape hatch: run fn(instance, *args) on the actor
            return ActorMethod(self, "__ray_call__")
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}")
        return ActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._class_name, self._method_names))


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        self._lock = threading.Lock()
        self._blob: Optional[bytes] = None
        self._class_id: Optional[str] = None
        self._registered_with = None  # weakref.ref to the runtime
        self._norm_env = None
        self._norm_env_with = None  # weakref.ref to the runtime
        self._method_names = [
            name for name, member in inspect.getmembers(cls)
            if callable(member) and not name.startswith("__")
        ]

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        clone = ActorClass(self._cls, merged)
        clone._blob = self._blob
        clone._class_id = self._class_id
        return clone

    def _ensure_registered(self, runtime) -> str:
        with self._lock:
            if self._blob is None:
                self._blob = serialization.dumps(self._cls)
                digest = hashlib.sha1(self._blob).hexdigest()[:24]
                self._class_id = f"cls:{self._cls.__name__}:{digest}"
            cached = (self._registered_with()
                      if self._registered_with is not None else None)
            if cached is not runtime:  # weakref: id() could be recycled
                import weakref
                runtime.put_function(self._class_id, self._blob)
                self._registered_with = weakref.ref(runtime)
            return self._class_id

    def _normalized_env(self, rt):
        """Normalize (package/upload) the class's runtime_env once per
        runtime — re-zipping a large working_dir per Actor.remote()
        would cost seconds of driver CPU each call."""
        if self._options.get("runtime_env") is None:
            return None
        import weakref
        from ray_tpu.runtime_env import normalize_runtime_env
        with self._lock:
            cached_rt = (self._norm_env_with()
                         if self._norm_env_with is not None else None)
            if cached_rt is not rt:
                self._norm_env = normalize_runtime_env(
                    self._options["runtime_env"], rt)
                self._norm_env_with = weakref.ref(rt)
            return self._norm_env

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        class_id = self._ensure_registered(rt)
        opts = self._options
        actor_id = ActorID.from_random()
        cfg = get_config()
        from ray_tpu.runtime_env import (merge_runtime_envs,
                                         runtime_env_hash)
        renv = merge_runtime_envs(
            getattr(rt, "current_runtime_env", None),
            self._normalized_env(rt))
        trace_id, parent_span_id = submitting_trace_context()
        spec = TaskSpec(
            task_id=rt.next_task_id(),
            function_id=class_id,
            args=[value_to_arg(a, rt) for a in args],
            kwargs={k: value_to_arg(v, rt) for k, v in kwargs.items()},
            num_returns=1,
            resources=resources_from_options(opts, default_cpu=1.0),
            strategy=strategy_from_options(opts),
            max_retries=0,
            name=opts.get("name") or self._cls.__name__,
            actor_id=actor_id,
            is_actor_creation=True,
            max_restarts=opts.get("max_restarts",
                                  cfg.actor_default_max_restarts),
            max_concurrency=opts.get("max_concurrency", 1),
            actor_name=opts.get("name"),
            runtime_env=renv,
            runtime_env_hash=runtime_env_hash(renv) if renv else "",
            trace_id=trace_id,
            parent_span_id=parent_span_id,
        )
        handle = ActorHandle(actor_id, self._cls.__name__, self._method_names)
        name = opts.get("name")
        if rt.is_driver:
            rt.create_actor(spec, name=name)
        else:
            rt.create_actor(spec)
        if name:
            # Persist the handle for get_actor() lookups
            # (reference: named actors through the GCS).
            blob = serialization.dumps(handle)
            if rt.is_driver:
                rt.gcs.kv.put(name.encode(), blob, namespace="actor_handles")
            else:
                rt.gcs_call("kv_put", name.encode(), blob, "actor_handles")
        return handle

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "actor classes cannot be instantiated directly; use .remote()")

    def __reduce__(self):
        return (ActorClass, (self._cls, self._options))


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        blob = rt.gcs.kv.get(name.encode(), namespace="actor_handles")
    else:
        blob = rt.gcs_call("get_named_actor_handle", name)
    if blob is None:
        raise ValueError(f"no actor named {name!r}")
    return serialization.loads(blob)
